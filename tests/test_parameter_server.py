"""Parameter buffer + server/client transport tests (reference §4:
in-process HttpServer/SocketServer exercised via clients)."""

import threading

import jax
import numpy as np
import pytest

from elephas_tpu.parameter.buffer import ParameterBuffer
from elephas_tpu.parameter.server import HttpServer, LocalServer, SocketServer, make_server


def _params():
    return {
        "dense": {"w": np.ones((4, 4), dtype=np.float32), "b": np.zeros(4, dtype=np.float32)}
    }


def test_buffer_apply_delta_convention():
    """weights -= delta (delta = before - after, reference convention)."""
    buf = ParameterBuffer(_params(), lock=True)
    delta = {"dense": {"w": np.full((4, 4), 0.25, np.float32), "b": np.zeros(4, np.float32)}}
    buf.apply_delta(delta)
    out = buf.get_numpy()
    np.testing.assert_allclose(out["dense"]["w"], 0.75)
    assert buf.version == 1


def test_buffer_concurrent_updates_all_applied():
    """With the lock, no update is lost (unlike hogwild)."""
    buf = ParameterBuffer(_params(), lock=True)
    delta = {"dense": {"w": np.full((4, 4), 0.01, np.float32), "b": np.zeros(4, np.float32)}}

    def pusher():
        for _ in range(20):
            buf.apply_delta(delta)

    threads = [threading.Thread(target=pusher) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    out = buf.get_numpy()
    np.testing.assert_allclose(out["dense"]["w"], 1.0 - 80 * 0.01, rtol=1e-5)
    assert buf.version == 80


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_transport_get_update_roundtrip(server_cls):
    server = server_cls(_params(), lock=True, port=0)
    server.start()
    try:
        client = server.client()
        pulled = client.get_parameters()
        np.testing.assert_allclose(pulled["dense"]["w"], 1.0)
        delta = {
            "dense": {"w": np.full((4, 4), 0.5, np.float32), "b": np.ones(4, np.float32)}
        }
        client.update_parameters(delta)
        pulled2 = client.get_parameters()
        np.testing.assert_allclose(pulled2["dense"]["w"], 0.5)
        np.testing.assert_allclose(pulled2["dense"]["b"], -1.0)
        if hasattr(client, "close"):
            client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_authenticated_transport_roundtrip(server_cls):
    """With a shared HMAC key, get/update/barriers/health all work and
    the wire protocol is unchanged for the legitimate job (VERDICT r3
    #8: multi-host fits broadcast such a key over DCN by default)."""
    key = b"k" * 32
    server = server_cls(_params(), lock=True, port=0, auth_key=key)
    server.start()
    try:
        client = server.client()
        assert client.auth_key == key
        pulled = client.get_parameters()
        np.testing.assert_allclose(pulled["dense"]["w"], 1.0)
        delta = {
            "dense": {"w": np.full((4, 4), 0.5, np.float32), "b": np.ones(4, np.float32)}
        }
        client.update_parameters(delta)
        np.testing.assert_allclose(client.get_parameters()["dense"]["w"], 0.5)
        assert client.barrier_arrive("t") == 1
        assert client.barrier_count("t") == 1
        assert client.health() is True
        if hasattr(client, "close"):
            client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_unauthenticated_writes_rejected(server_cls):
    """A client WITHOUT the key (an attacker on the pod network) must not
    get a pickle into the server: updates and reads are refused before
    any ``pickle.loads`` and the buffer never changes."""
    from elephas_tpu.parameter.client import (
        HttpClient, ParameterServerUnavailable, SocketClient,
    )

    key = b"s" * 32
    server = server_cls(_params(), lock=True, port=0, auth_key=key)
    server.start()
    try:
        cls = HttpClient if server_cls is HttpServer else SocketClient
        for bad_key in (None, b"wrong" * 8):
            intruder = cls(f"127.0.0.1:{server.port}", auth_key=bad_key)
            delta = {
                "dense": {"w": np.ones((4, 4), np.float32), "b": np.ones(4, np.float32)}
            }
            with pytest.raises((RuntimeError, ParameterServerUnavailable, ConnectionError)):
                intruder.update_parameters(delta)
            with pytest.raises((RuntimeError, ParameterServerUnavailable, ConnectionError)):
                intruder.get_parameters()
            if hasattr(intruder, "close"):
                intruder.close()
        assert server.buffer.version == 0  # nothing was ever applied
        np.testing.assert_allclose(server.buffer.get_numpy()["dense"]["w"], 1.0)
    finally:
        server.stop()


def test_socket_replay_frame_rejected():
    """A captured authenticated socket frame replayed verbatim must be
    refused (nonce replay) without touching the buffer — an HMAC alone
    authenticates the sender, not the occasion."""
    import pickle
    import socket as socket_mod
    import struct
    import time as time_mod

    from elephas_tpu.utils import sockets as su

    key = b"r" * 32
    server = SocketServer(_params(), lock=True, port=0, auth_key=key)
    server.start()
    try:
        delta = {
            "dense": {"w": np.full((4, 4), 0.5, np.float32), "b": np.ones(4, np.float32)}
        }
        payload = pickle.dumps(("u", delta), protocol=pickle.HIGHEST_PROTOCOL)
        header = b"\x07" * 16 + struct.pack("!d", time_mod.time())
        body = header + payload
        frame = struct.pack("!Q", len(body) + 32) + su.frame_mac(key, body) + body

        def send_raw(expect_ok: bool) -> bool:
            sock = socket_mod.create_connection(("127.0.0.1", server.port), timeout=5)
            try:
                sock.settimeout(5)
                sock.sendall(frame)
                try:
                    # server's "ok" — reply MAC is bound to OUR nonce
                    su.receive(sock, key=key, bind=b"\x07" * 16)
                    return True
                except (ConnectionError, OSError, socket_mod.timeout):
                    return False
            finally:
                sock.close()

        assert send_raw(True) is True  # first delivery applies
        assert server.buffer.version == 1
        assert send_raw(False) is False  # verbatim replay: refused
        assert server.buffer.version == 1  # nothing double-applied
    finally:
        server.stop()


def test_socket_response_bound_to_request_nonce():
    """Socket replies are MAC-bound to the request's nonce (advisor r4,
    mirroring the HTTP transport): the same reply bytes verify under the
    request nonce and FAIL verification under any other — so a captured
    response can't be replayed into a later exchange."""
    import socket as socket_mod

    from elephas_tpu.utils import sockets as su

    key = b"b" * 32
    server = SocketServer(_params(), lock=True, port=0, auth_key=key)
    server.start()
    try:
        sock = socket_mod.create_connection(("127.0.0.1", server.port), timeout=5)
        try:
            sock.settimeout(5)
            nonce = su.send(sock, ("c", "tag"), key=key)
            assert len(nonce) == 16
            # Capture the raw reply and check the MAC binding directly.
            import struct

            (length,) = struct.unpack("!Q", su._recv_exact(sock, 8))
            data = su._recv_exact(sock, length)
            tag, body = data[:32], data[32:]
            assert tag == su.frame_mac(key, nonce + body)  # bound to request
            assert tag != su.frame_mac(key, body)  # unbound check fails
            assert tag != su.frame_mac(key, b"\x01" * 16 + body)  # other nonce
        finally:
            sock.close()
    finally:
        server.stop()


def test_replay_guard_future_timestamp_retention():
    """A frame whose sender clock runs AHEAD stays replay-protected for
    its WHOLE freshness life (advisor r4): the nonce must be retained
    until ts + window, not receipt + window — otherwise the frame
    replays in the gap after its nonce is pruned but before freshness
    expires."""
    import time as time_mod

    from elephas_tpu.utils.sockets import ReplayGuard

    guard = ReplayGuard(window=300.0)
    ahead = time_mod.time() + 200  # sender clock 200s fast: still fresh
    guard.check(b"n" * 16, ahead)
    # The expiry must outlive receipt+window whenever ts > receipt.
    expiry = guard._order[-1][0]
    assert expiry >= ahead + 300.0 - 1.0
    with pytest.raises(ConnectionError, match="replayed"):
        guard.check(b"n" * 16, ahead)


def test_http_replay_request_rejected():
    """Replaying a captured authenticated HTTP update (same nonce/ts/mac)
    is a 403; the first delivery applied exactly once."""
    import pickle
    import time as time_mod
    import urllib.error
    import urllib.request

    from elephas_tpu.utils import sockets as su

    key = b"h" * 32
    server = HttpServer(_params(), lock=True, port=0, auth_key=key)
    server.start()
    try:
        delta = {
            "dense": {"w": np.full((4, 4), 0.5, np.float32), "b": np.ones(4, np.float32)}
        }
        body = pickle.dumps(delta, protocol=pickle.HIGHEST_PROTOCOL)
        nonce = b"\x09" * 16
        ts = repr(time_mod.time())
        mac = su.frame_mac(
            key, b"POST" + b"/update" + nonce + ts.encode() + body
        ).hex()

        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{server.port}/update", data=body, method="POST",
                headers={"X-Elephas-Nonce": nonce.hex(), "X-Elephas-TS": ts,
                         "X-Elephas-Auth": mac},
            )
            with urllib.request.urlopen(req, timeout=5) as r:
                return r.status

        assert post() == 200
        assert server.buffer.version == 1
        with pytest.raises(urllib.error.HTTPError) as err:
            post()
        assert err.value.code == 403
        assert server.buffer.version == 1
    finally:
        server.stop()


def test_local_server_shares_buffer():
    server = LocalServer(_params(), lock=False)
    client_a, client_b = server.client(), server.client()
    delta = {"dense": {"w": np.full((4, 4), 1.0, np.float32), "b": np.zeros(4, np.float32)}}
    client_a.update_parameters(delta)
    np.testing.assert_allclose(
        np.asarray(jax.device_get(client_b.get_parameters())["dense"]["w"]), 0.0
    )


def test_make_server_factory():
    assert isinstance(make_server("local", _params()), LocalServer)
    assert isinstance(make_server("http", _params(), port=0), HttpServer)
    assert isinstance(make_server("socket", _params(), port=0), SocketServer)
    with pytest.raises(ValueError):
        make_server("flask", _params())


def test_wire_servers_bind_loopback_by_default():
    # ADVICE r1: unauthenticated pickle transports must not listen on all
    # interfaces unless explicitly asked to.
    from elephas_tpu.parameter.server import HttpServer, SocketServer

    params = {"params": {"w": np.zeros(2, np.float32)}, "batch_stats": {}}
    for cls in (HttpServer, SocketServer):
        srv = cls(params, port=0)
        assert srv.host == "127.0.0.1"
        srv2 = cls(params, port=0, host="0.0.0.0")
        assert srv2.host == "0.0.0.0"


def test_buffer_get_with_version_and_set_bump():
    buf = ParameterBuffer(_params(), lock=True)
    ver0, snap0 = buf.get_numpy_with_version()
    assert ver0 == 0
    np.testing.assert_allclose(snap0["dense"]["w"], 1.0)
    delta = {"dense": {"w": np.full((4, 4), 0.25, np.float32),
                       "b": np.zeros(4, np.float32)}}
    buf.apply_delta(delta)
    ver1, snap1 = buf.get_numpy_with_version()
    assert ver1 == 1
    np.testing.assert_allclose(snap1["dense"]["w"], 0.75)
    buf.set(_params())  # set() must ALSO invalidate version-gated caches
    assert buf.version == 2


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_packed_pull_uses_not_modified_cache(server_cls):
    """Second pull of an unchanged buffer must be answered by the tiny
    not-modified frame (counted in ps_cache_hit_total), and apply_delta
    must invalidate: the next pull carries the full fresh tree."""
    from elephas_tpu import obs

    hit_counter = obs.default_registry().counter("ps_cache_hit_total")
    server = server_cls(_params(), lock=True, port=0)
    server.start()
    try:
        client = server.client()
        first = client.get_parameters()
        np.testing.assert_allclose(first["dense"]["w"], 1.0)
        before = hit_counter.value
        second = client.get_parameters()  # unchanged → not-modified reply
        assert hit_counter.value == before + 1
        np.testing.assert_allclose(second["dense"]["w"], 1.0)

        delta = {"dense": {"w": np.full((4, 4), 0.5, np.float32),
                           "b": np.zeros(4, np.float32)}}
        client.update_parameters(delta)  # bumps version → cache invalid
        third = client.get_parameters()
        np.testing.assert_allclose(third["dense"]["w"], 0.5)
        assert hit_counter.value == before + 1  # full body, not a hit
        if hasattr(client, "close"):
            client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_packed_roundtrip_is_bit_exact(server_cls):
    """The default packed codec must move arbitrary float bits exactly
    (async/hogwild numerical equivalence depends on it)."""
    rng = np.random.default_rng(7)
    params = {"dense": {"w": rng.normal(size=(4, 4)).astype(np.float32),
                        "b": rng.normal(size=(4,)).astype(np.float32)}}
    server = server_cls(params, lock=True, port=0)
    server.start()
    try:
        client = server.client()
        pulled = client.get_parameters()
        np.testing.assert_array_equal(pulled["dense"]["w"], params["dense"]["w"])
        np.testing.assert_array_equal(pulled["dense"]["b"], params["dense"]["b"])
        delta = {"dense": {"w": rng.normal(size=(4, 4)).astype(np.float32),
                           "b": np.zeros(4, np.float32)}}
        client.update_parameters(delta)
        np.testing.assert_array_equal(
            client.get_parameters()["dense"]["w"],
            params["dense"]["w"] - delta["dense"]["w"])
        if hasattr(client, "close"):
            client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_legacy_pickle_client_interop(server_cls):
    """codec='pickle' clients (stand-ins for pre-wire peers) speak the
    legacy protocol against the NEW servers: pulls and pushes both work."""
    from elephas_tpu.parameter.client import HttpClient, SocketClient

    server = server_cls(_params(), lock=True, port=0)
    server.start()
    try:
        cls = HttpClient if server_cls is HttpServer else SocketClient
        legacy = cls(f"127.0.0.1:{server.port}", codec="pickle")
        pulled = legacy.get_parameters()
        np.testing.assert_allclose(pulled["dense"]["w"], 1.0)
        delta = {"dense": {"w": np.full((4, 4), 0.5, np.float32),
                           "b": np.zeros(4, np.float32)}}
        legacy.update_parameters(delta)
        np.testing.assert_allclose(legacy.get_parameters()["dense"]["w"], 0.5)
        # Packed and pickle clients see the SAME buffer state.
        packed = server.client()
        np.testing.assert_allclose(packed.get_parameters()["dense"]["w"], 0.5)
        for c in (legacy, packed):
            if hasattr(c, "close"):
                c.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_quantized_push_applies_approximately(server_cls):
    server = server_cls(_params(), lock=True, port=0)
    server.start()
    try:
        client = server.client()
        client.push_quantize = None  # construct via factory arg instead
        from elephas_tpu.parameter.client import HttpClient, SocketClient

        cls = HttpClient if server_cls is HttpServer else SocketClient
        qclient = cls(f"127.0.0.1:{server.port}", push_quantize="bf16")
        delta = {"dense": {"w": np.full((4, 4), 0.5, np.float32),
                           "b": np.zeros(4, np.float32)}}
        qclient.update_parameters(delta)
        out = qclient.get_parameters()
        np.testing.assert_allclose(out["dense"]["w"], 0.5, rtol=1e-2)
        for c in (client, qclient):
            if hasattr(c, "close"):
                c.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_authenticated_packed_roundtrip(server_cls):
    """HMAC + packed codec compose: scatter-gather frames are MAC'd
    chunk-wise and verified before decode."""
    key = b"p" * 32
    server = server_cls(_params(), lock=True, port=0, auth_key=key)
    server.start()
    try:
        client = server.client()
        np.testing.assert_allclose(client.get_parameters()["dense"]["w"], 1.0)
        delta = {"dense": {"w": np.full((4, 4), 0.5, np.float32),
                           "b": np.zeros(4, np.float32)}}
        client.update_parameters(delta)
        np.testing.assert_allclose(client.get_parameters()["dense"]["w"], 0.5)
        # Cached not-modified path works under auth too.
        np.testing.assert_allclose(client.get_parameters()["dense"]["w"], 0.5)
        if hasattr(client, "close"):
            client.close()
    finally:
        server.stop()


def test_ps_byte_counters_move():
    from elephas_tpu import obs

    reg = obs.default_registry()
    tx = reg.counter("ps_bytes_tx_total", labelnames=("transport",))
    rx = reg.counter("ps_bytes_rx_total", labelnames=("transport",))
    tx0 = tx.labels(transport="http").value
    rx0 = rx.labels(transport="http").value
    server = HttpServer(_params(), lock=True, port=0)
    server.start()
    try:
        client = server.client()
        client.get_parameters()
        # pull left the server, on the http transport's label child
        assert tx.labels(transport="http").value > tx0
        delta = {"dense": {"w": np.full((4, 4), 0.5, np.float32),
                           "b": np.zeros(4, np.float32)}}
        client.update_parameters(delta)
        assert rx.labels(transport="http").value > rx0  # push reached it
    finally:
        server.stop()


def test_trace_id_survives_kill_and_warm_restart(tmp_path):
    """THE distributed-trace propagation invariant: a client pushing
    inside an active trace context makes the PS-side handle spans
    children of that trace ACROSS the socket — and across a kill plus
    warm restart on the same port, the trace id stays the client's while
    the boot id changes, so a merged trace shows one causal chain
    through two server incarnations. The kill also dumps the flight
    recorder next to the WAL."""
    import json
    import os

    from elephas_tpu import obs

    wal_dir = str(tmp_path / "wal")
    os.makedirs(wal_dir)
    delta = {"dense": {"w": np.full((4, 4), 0.25, np.float32),
                       "b": np.zeros(4, np.float32)}}
    tr = obs.enable_tracing(capacity=1024, annotate_device=False)
    obs.default_flight_recorder().clear()  # hermetic vs earlier kills
    try:
        server = SocketServer(_params(), lock=True, port=0, wal_dir=wal_dir)
        server.start()
        port, boot1 = server.port, server.boot
        ctx = obs.new_context()
        client = server.client()
        with obs.activate(ctx):
            client.update_parameters(delta)
        client.close()
        server.kill()
        assert server.flight_dump and os.path.exists(server.flight_dump)
        dump = json.loads(open(server.flight_dump).read())
        assert dump["counts_by_kind"]["ps_kill"] == 1

        # Warm restart: same port, same WAL, NEW boot id.
        fresh = SocketServer(_params(), lock=True, port=port,
                             wal_dir=wal_dir)
        fresh.start()
        boot2 = fresh.boot
        assert boot2 != boot1
        assert fresh.buffer.version >= 1  # WAL superseded the cold init
        client2 = fresh.client()
        with obs.activate(ctx):  # the unit's trace continues
            client2.update_parameters(delta)
        client2.close()
        fresh.stop()

        handles = [e for e in tr.events() if e.name == "ps/handle_push"]
        assert len(handles) == 2
        assert {e.args["boot"] for e in handles} == {boot1, boot2}
        assert all(e.trace_id == ctx.trace_id for e in handles)
        pushes = [e for e in tr.events() if e.name == "ps/push"]
        assert pushes and all(e.trace_id == ctx.trace_id for e in pushes)
        # The handle span's parent is the client's ps/push span — the
        # exact (trace_id, span_id) pair the wire header shipped.
        push_ids = {e.span_id for e in pushes}
        assert all(e.parent_id in push_ids for e in handles)
        applies = [e for e in tr.events() if e.name == "ps/apply"]
        assert len(applies) == 2
        assert all(e.trace_id == ctx.trace_id for e in applies)
    finally:
        obs.disable_tracing()


# --------------------------------------------------------------------------
# Bounded-staleness admission (AdmissionPolicy + transports)
# --------------------------------------------------------------------------


def _zero_delta():
    return {"dense": {"w": np.zeros((4, 4), np.float32),
                      "b": np.zeros(4, np.float32)}}


def _advance_version(server, n):
    """Bump the buffer version by n zero deltas from a peer that never
    pulled (unstamped → always admitted, buffer values unchanged)."""
    fresh = server.client()
    for _ in range(n):
        fresh.update_parameters(_zero_delta())
    if hasattr(fresh, "close"):
        fresh.close()


def test_admission_policy_decide_regimes():
    from elephas_tpu.parameter.server import AdmissionPolicy

    policy = AdmissionPolicy(max_staleness=8, soft=2)
    assert policy.decide(None) == ("accept", 1.0)  # unstamped peers
    assert policy.decide(2) == ("accept", 1.0)  # at the soft bound
    verdict, weight = policy.decide(5)
    assert verdict == "damp" and weight == pytest.approx(1.0 / 4.0)
    assert policy.decide(8)[0] == "damp"  # at max: still applied
    assert policy.decide(9) == ("reject", 0.0)
    assert AdmissionPolicy().decide(10 ** 6) == ("accept", 1.0)


def test_admission_env_knobs(monkeypatch):
    from elephas_tpu.parameter.server import AdmissionPolicy

    monkeypatch.setenv("ELEPHAS_MAX_STALENESS", "4")
    monkeypatch.setenv("ELEPHAS_STALENESS_SOFT", "1")
    policy = AdmissionPolicy()
    assert policy.max_staleness == 4 and policy.soft == 1
    assert AdmissionPolicy(max_staleness=9).max_staleness == 9  # arg wins
    monkeypatch.setenv("ELEPHAS_MAX_STALENESS", "plenty")
    with pytest.warns(RuntimeWarning, match="ELEPHAS_MAX_STALENESS"):
        assert AdmissionPolicy().max_staleness is None  # warn, don't crash


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_stale_push_rejected_with_typed_error(server_cls):
    """Past the hard bound the push must NOT apply: the client gets the
    typed StaleDeltaRejected (with the measured lag and the bound), the
    buffer is untouched, and the ledger counts a rejection WITHOUT an
    update — rejected work must not read as contribution."""
    from elephas_tpu import obs
    from elephas_tpu.parameter.client import StaleDeltaRejected

    rejected = obs.default_registry().counter(
        "ps_delta_rejected_total", labelnames=("reason",))
    before = rejected.labels(reason="max_staleness").value
    server = server_cls(_params(), lock=True, port=0,
                        max_staleness=2, staleness_soft=2)
    server.start()
    try:
        stale = server.client()
        stale.worker_id = "laggard"
        stale.get_parameters()  # trains against version 0
        _advance_version(server, 3)  # the fleet moves on
        delta = {"dense": {"w": np.full((4, 4), 0.4, np.float32),
                           "b": np.zeros(4, np.float32)}}
        with pytest.raises(StaleDeltaRejected) as err:
            stale.update_parameters(delta)
        assert err.value.lag == 3
        assert err.value.max_staleness == 2
        assert err.value.version == 3  # the server's live version line
        assert server.buffer.version == 3  # reject never applied
        np.testing.assert_allclose(
            server.buffer.get_numpy()["dense"]["w"], 1.0)
        row = server.ledger.snapshot()["workers"]["laggard"]
        assert row["rejected"] == 1
        assert row["updates"] == 0  # accounting regression guard
        assert rejected.labels(reason="max_staleness").value == before + 1
        # Recovery protocol: re-pull, then the same delta is fresh.
        stale.get_parameters()
        stale.update_parameters(delta)
        np.testing.assert_allclose(
            server.buffer.get_numpy()["dense"]["w"], 0.6)
        assert server.ledger.snapshot()["workers"]["laggard"]["updates"] == 1
        if hasattr(stale, "close"):
            stale.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_stale_push_damped_between_soft_and_max(server_cls):
    """Inside (soft, max] the delta applies at the 1/(1+lag-soft) decay
    weight — and counts as BOTH an update and a damped apply."""
    from elephas_tpu import obs

    damped = obs.default_registry().counter("ps_delta_damped_total")
    before = damped.value
    server = server_cls(_params(), lock=True, port=0,
                        max_staleness=10, staleness_soft=1)
    server.start()
    try:
        client = server.client()
        client.worker_id = "behind"
        client.get_parameters()  # version 0
        _advance_version(server, 3)
        delta = {"dense": {"w": np.full((4, 4), 0.6, np.float32),
                           "b": np.zeros(4, np.float32)}}
        client.update_parameters(delta)  # lag 3 → weight 1/3
        np.testing.assert_allclose(
            server.buffer.get_numpy()["dense"]["w"], 0.8, rtol=1e-6)
        row = server.ledger.snapshot()["workers"]["behind"]
        assert row["damped"] == 1 and row["updates"] == 1
        assert row["lag_max"] == 3
        assert damped.value == before + 1
        if hasattr(client, "close"):
            client.close()
    finally:
        server.stop()


@pytest.mark.parametrize("server_cls", [HttpServer, SocketServer])
def test_legacy_unstamped_push_ignores_bounds(server_cls):
    """Pre-policy peers (pickle codec, no seen_version stamp) keep
    their exact old behavior even under the tightest bounds: full-weight
    apply, counted as unstamped coverage."""
    from elephas_tpu.parameter.client import HttpClient, SocketClient

    server = server_cls(_params(), lock=True, port=0,
                        max_staleness=0, staleness_soft=0)
    server.start()
    try:
        _advance_version(server, 2)  # any stamped lag would now reject
        cls = HttpClient if server_cls is HttpServer else SocketClient
        legacy = cls(f"127.0.0.1:{server.port}", codec="pickle")
        delta = {"dense": {"w": np.full((4, 4), 0.5, np.float32),
                           "b": np.zeros(4, np.float32)}}
        legacy.update_parameters(delta)
        assert server.buffer.version == 3
        np.testing.assert_allclose(
            server.buffer.get_numpy()["dense"]["w"], 0.5)
        snap = server.ledger.snapshot()
        assert snap["unstamped_updates"] >= 3
        if hasattr(legacy, "close"):
            legacy.close()
    finally:
        server.stop()


def test_sharded_group_surfaces_rejection():
    """Admission is per shard; a StaleDeltaRejected from any member
    propagates through the sharded client's fanout, and a re-pull
    resyncs every sub-cache so the retry is fresh."""
    from elephas_tpu.parameter.client import StaleDeltaRejected
    from elephas_tpu.parameter.group import ShardGroup

    group = ShardGroup(_params(), 2, mode="socket", max_staleness=1)
    group.start()
    try:
        client = group.client()
        client.get_parameters()  # each shard caches its version 0
        other = group.client()
        for _ in range(2):  # every shard's version line moves to 2
            other.update_parameters(_zero_delta())
        delta = {"dense": {"w": np.full((4, 4), 0.25, np.float32),
                           "b": np.zeros(4, np.float32)}}
        with pytest.raises(StaleDeltaRejected) as err:
            client.update_parameters(delta)
        assert err.value.lag == 2 and err.value.max_staleness == 1
        client.get_parameters()  # recovery: resync all K sub-caches
        client.update_parameters(delta)
        np.testing.assert_allclose(
            group.get_parameters()["dense"]["w"], 0.75)
        client.close()
        other.close()
    finally:
        group.stop()


def test_prob_losses_match_logit_losses():
    import jax.numpy as jnp
    from elephas_tpu.engine.losses import LOSSES

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(16, 5)).astype(np.float32))
    onehot = jnp.asarray(np.eye(5, dtype=np.float32)[rng.integers(0, 5, 16)])
    probs = jax.nn.softmax(logits, axis=-1)
    np.testing.assert_allclose(
        LOSSES["categorical_crossentropy_probs"](probs, onehot),
        LOSSES["categorical_crossentropy"](logits, onehot),
        rtol=1e-5, atol=1e-5,
    )
    labels = jnp.argmax(onehot, axis=-1)
    np.testing.assert_allclose(
        LOSSES["sparse_categorical_crossentropy_probs"](probs, labels),
        LOSSES["sparse_categorical_crossentropy"](logits, labels),
        rtol=1e-5, atol=1e-5,
    )
    blogits = jnp.asarray(rng.normal(size=(16, 1)).astype(np.float32))
    btargets = jnp.asarray(rng.integers(0, 2, (16, 1)).astype(np.float32))
    np.testing.assert_allclose(
        LOSSES["binary_crossentropy_probs"](jax.nn.sigmoid(blogits), btargets),
        LOSSES["binary_crossentropy"](blogits, btargets),
        rtol=1e-4, atol=1e-5,
    )
