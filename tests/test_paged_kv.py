"""Paged KV pool: allocator invariants, copy-on-write, prefix caching,
and chunked-prefill token identity.

The block allocator's contract is conservation — a block is free iff
its refcount is 0, and the refcount equals the number of holders (slot
rows + prefix-cache entries) at all times, including after adversarial
seeded churn. The serving contract is identity: the paged layout and
the chunked prefill program must emit EXACTLY the tokens the contiguous
oracle (``paged=False``) and the per-row ``generate()`` oracle emit,
over the full matrix (ragged prompts, EOS stops, deadline evictions,
prefix-cache hits, per-step chunk budgets).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu import obs
from elephas_tpu.api.compile import CompiledModel
from elephas_tpu.models import get_model
from elephas_tpu.obs.flight import FlightRecorder
from elephas_tpu.serving import (
    DonatedBufferError,
    InferenceEngine,
    PagedKVPool,
    PrefixCache,
)
from tests.test_serving import FakeClock, _per_row

VOCAB, SEQ = 97, 64


@pytest.fixture(scope="module")
def compiled():
    return CompiledModel(
        get_model(
            "transformer_lm", vocab_size=VOCAB, d_model=32, num_heads=4,
            num_layers=2, max_seq_len=SEQ,
        ),
        optimizer={"name": "adam", "learning_rate": 3e-3},
        loss="sparse_categorical_crossentropy",
        metrics=[],
        input_shape=(SEQ,),
        input_dtype=jnp.int32,
        seed=0,
    )


@pytest.fixture()
def flight():
    previous = obs.default_flight_recorder()
    recorder = FlightRecorder(capacity=256)
    obs.set_default_flight_recorder(recorder)
    try:
        yield recorder
    finally:
        obs.set_default_flight_recorder(previous)


def _pool(compiled, max_slots=3, max_len=24, **kw):
    decode_module = dataclasses.replace(
        compiled.module, decode=True, attention="dense"
    )
    kw.setdefault("block_size", 4)
    return PagedKVPool(decode_module, max_slots, max_len, **kw)


def _paged_engine(compiled, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("max_len", 24)
    kw.setdefault("queue_depth", 8)
    kw.setdefault("paged", True)
    return InferenceEngine(compiled, **kw)


# -- allocator invariants ----------------------------------------------------


def test_block_acquire_release_refcount_invariants(compiled):
    pool = _pool(compiled)
    assert pool.free_blocks == pool.num_blocks
    slot = pool.acquire()
    pool.ensure_cols(slot, 10)  # 3 blocks at block_size=4
    assert pool.blocks_in_use == 3
    held = [int(b) for b in pool.table.rows[slot] if b >= 0]
    assert len(held) == 3 and all(pool._ref[b] == 1 for b in held)
    pool.assert_block_invariants()
    pool.release(slot)  # no chain: every block must come back
    assert pool.free_blocks == pool.num_blocks
    assert all(pool._ref[b] == 0 for b in held)
    pool.assert_block_invariants()


def test_slot_double_release_raises(compiled):
    pool = _pool(compiled)
    slot = pool.acquire()
    pool.ensure_cols(slot, 4)
    pool.release(slot)
    with pytest.raises(ValueError, match="already free"):
        pool.release(slot)


def test_block_double_release_fails_loudly(compiled):
    pool = _pool(compiled)
    slot = pool.acquire()
    pool.ensure_cols(slot, 4)
    block = int(pool.table.rows[slot, 0])
    pool._decref(block)  # simulate a corrupt row releasing early
    with pytest.raises(RuntimeError, match="double-released"):
        pool._decref(block)


def test_wholesale_admit_is_refused(compiled):
    pool = _pool(compiled)
    with pytest.raises(RuntimeError, match="no wholesale admit"):
        pool.admit(0, None, 0)


def test_undersized_pool_dead_ends_loudly(compiled):
    """With no prefix cache to evict, exhausting the blocks raises the
    sizing error instead of looping."""
    pool = _pool(compiled, max_slots=2, num_blocks=6, prefix_cache=False)
    a, b = pool.acquire(), pool.acquire()
    pool.ensure_cols(a, pool.virtual_len)  # 6 blocks: takes everything
    with pytest.raises(RuntimeError, match="out of KV blocks"):
        pool.ensure_cols(b, 4)


def test_cow_fork_preserves_content_and_isolates_writes(compiled):
    pool = _pool(compiled)
    parent = pool.acquire()
    pool.ensure_cols(parent, 8)
    pblock = int(pool.table.rows[parent, 0])
    # Stamp recognizable K/V into the parent's first block.
    pool.swap(jax.tree_util.tree_map(
        lambda leaf: leaf.at[pblock].set(7.5) if leaf.ndim == 4 else leaf,
        pool.cache,
    ))
    child = pool.fork_slot(parent)
    assert child is not None
    assert int(pool.table.rows[child, 0]) == pblock  # aliased, not copied
    assert pool._ref[pblock] == 2
    fresh = pool.ensure_writable(child, 0)  # a "write" hits the COW guard
    assert fresh != pblock
    assert int(pool.table.rows[parent, 0]) == pblock
    assert pool._ref[pblock] == 1 and pool._ref[fresh] == 1
    for leaf in jax.tree_util.tree_leaves(pool.cache):
        if leaf.ndim == 4:
            # The fork's block is a faithful copy of the shared content.
            np.testing.assert_array_equal(
                np.asarray(leaf[fresh]), np.asarray(leaf[pblock])
            )
    # Writing the fork's copy must not touch the parent's block.
    pool.swap(jax.tree_util.tree_map(
        lambda leaf: leaf.at[fresh].set(-3.0) if leaf.ndim == 4 else leaf,
        pool.cache,
    ))
    leaf = next(l for l in jax.tree_util.tree_leaves(pool.cache)
                if l.ndim == 4)
    assert float(np.asarray(leaf[pblock]).max()) == 7.5
    pool.assert_block_invariants()


def test_ensure_cols_rejects_past_virtual_length(compiled):
    pool = _pool(compiled)
    slot = pool.acquire()
    with pytest.raises(ValueError, match="columns"):
        pool.ensure_cols(slot, pool.virtual_len + 1)


# -- prefix cache ------------------------------------------------------------


def test_prefix_cache_matches_longest_strictly_shorter_prefix():
    cache = PrefixCache(block_size=4)
    incref = lambda b: None
    cache.insert((1, 2, 3, 4, 5, 6, 7, 8), [10, 11], incref)
    assert len(cache) == 2  # every full-block prefix registered
    matched, blocks = cache.match((1, 2, 3, 4, 5, 6, 7, 8, 9))
    assert matched == 8 and blocks == [10, 11]
    # The exact chain is capped one block short: >= 1 token must prefill.
    matched, blocks = cache.match((1, 2, 3, 4, 5, 6, 7, 8))
    assert matched == 4 and blocks == [10]
    assert cache.match((9, 9, 9, 9, 9))[0] == 0
    assert cache.hits_total == 2 and cache.lookups_total == 3
    assert cache.tokens_saved_total == 12


def test_release_publishes_full_block_chain(compiled):
    pool = _pool(compiled)
    slot = pool.acquire()
    pool.ensure_cols(slot, 10)
    chain = list(range(30, 40))  # 10 tokens -> 2 full blocks resident
    held = [int(b) for b in pool.table.rows[slot][:2]]
    pool.release(slot, tokens=chain)
    assert len(pool.prefix) == 2
    assert all(pool._ref[b] > 0 for b in held)  # pinned by the cache
    matched, blocks = pool.prefix.match(tuple(chain))
    assert matched == 8 and blocks == held
    pool.assert_block_invariants()


def test_lru_eviction_under_pressure_notes_flight(compiled, flight):
    """Allocation pressure evicts the LEAST-recently-used resident
    prefix (flight kind ``prefix_evict``), never a slot-held block."""
    pool = _pool(compiled, max_slots=2, max_len=8, block_size=4)
    assert pool.num_blocks == 4
    for start in (0, 40):  # two resident 1-block chains
        slot = pool.acquire()
        pool.ensure_cols(slot, 4)
        pool.release(slot, tokens=list(range(start, start + 4)))
    assert pool.free_blocks == 2 and len(pool.prefix) == 2
    pool.prefix.match(tuple(range(0, 4)) + (9,))  # freshen the first chain
    a, b = pool.acquire(), pool.acquire()
    pool.ensure_cols(a, pool.virtual_len)  # 2 blocks: drains the free list
    pool.ensure_cols(b, 4)  # 3rd block only exists by evicting a prefix
    assert len(pool.prefix) == 1  # LRU (the 40.. chain) was evicted
    assert pool.prefix.match(tuple(range(0, 4)) + (9,))[0] == 4
    events = flight.events(kind="prefix_evict")
    assert len(events) == 1
    assert events[0].detail["blocks"] == 1
    assert pool.prefix.evictions_total == 1
    pool.assert_block_invariants()


def test_free_count_conservation_after_seeded_churn(compiled):
    """Adversarial churn — admissions with shared prefixes, forks, COW
    writes, chain-publishing releases — conserves every block: the
    invariant checker passes at every step and all blocks are accounted
    for at the end."""
    rng = np.random.default_rng(0)
    pool = _pool(compiled, max_slots=4, max_len=16, block_size=4)
    live = {}  # slot -> token chain
    vocab = list(range(50, 60))
    for _ in range(200):
        op = rng.choice(["admit", "grow", "fork", "release"])
        try:
            if op == "admit" and pool.free_count > 0:
                slot = pool.acquire()
                prompt = [int(rng.choice(vocab))
                          for _ in range(int(rng.integers(1, 9)))]
                pool.admit_prefix(slot, prompt)
                live[slot] = prompt
                pool.ensure_cols(slot, len(prompt))
            elif op == "grow" and live:
                slot = int(rng.choice(list(live)))
                upto = min(len(live[slot]) + int(rng.integers(0, 5)),
                           pool.virtual_len)
                pool.ensure_cols(slot, upto)
                live[slot] += [int(rng.choice(vocab))
                               for _ in range(upto - len(live[slot]))]
                pool.ensure_writable(slot, upto - 1)
            elif op == "fork" and live and pool.free_count > 0:
                parent = int(rng.choice(list(live)))
                child = pool.fork_slot(parent)
                if child is not None:
                    live[child] = list(live[parent])
            elif op == "release" and live:
                slot = int(rng.choice(list(live)))
                pool.release(slot, tokens=live.pop(slot))
        except RuntimeError as e:
            # COW copies under full occupancy can legitimately exhaust
            # the pool; partial allocation must still conserve blocks.
            assert "out of KV blocks" in str(e)
        pool.assert_block_invariants()
    for slot in list(live):
        pool.release(slot, tokens=live.pop(slot))
    pool.assert_block_invariants()
    # Every block is either free or pinned by a resident prefix entry.
    resident = {b for e in pool.prefix._entries.values()
                for b in e.blocks}
    assert pool.free_blocks + len(resident) == pool.num_blocks


# -- serving identity --------------------------------------------------------


def _serve_all(eng, prompts, max_new_tokens=10, **submit_kw):
    rids = [eng.submit(p, max_new_tokens=max_new_tokens, **submit_kw)
            for p in prompts]
    return [eng.result(r, timeout_s=120).tokens for r in rids]


PROMPTS = [[5, 3, 9], [1, 2, 3, 4, 5, 6, 7], [11, 12]]


def test_paged_identical_to_contiguous_oracle(compiled):
    """THE tentpole pin: the paged layout (gather → same apply →
    scatter) emits exactly the contiguous pool's tokens, at one prefill
    and one decode compile, across block sizes that do and don't divide
    the prompt/cache lengths."""
    oracle = None
    for kw in (dict(paged=False), dict(paged=True),
               dict(paged=True, kv_block_size=4),
               dict(paged=True, kv_block_size=5)):
        eng = _paged_engine(compiled, **kw)
        got = _serve_all(eng, PROMPTS)
        st = eng.stats()
        assert st["prefill_traces"] == 1 and st["decode_traces"] == 1
        if oracle is None:
            oracle = got
        else:
            assert got == oracle, kw
    for prompt, tokens in zip(PROMPTS, oracle):
        assert tokens == _per_row(compiled, prompt, 10)


def test_chunked_prefill_identical_to_one_shot(compiled):
    """Chunked prefill is the same math as one-shot (causal attention
    decomposes over chunks): every chunk width and per-step budget
    yields the per-row oracle's tokens, still one compile each."""
    for chunk, per_step in ((3, None), (3, 1), (2, 2), (1, 1)):
        eng = _paged_engine(compiled, kv_block_size=4, prefill_chunk=chunk,
                            prefill_chunks_per_step=per_step)
        got = _serve_all(eng, PROMPTS)
        st = eng.stats()
        assert st["prefill_traces"] == 1 and st["decode_traces"] == 1
        for prompt, tokens in zip(PROMPTS, got):
            assert tokens == _per_row(compiled, prompt, 10), (chunk, per_step)


def test_chunked_prefill_eos_stop_identity(compiled):
    free = _per_row(compiled, [5, 3, 9], 10)
    stop = free[3]
    eng = _paged_engine(compiled, stop_token=stop, kv_block_size=4,
                        prefill_chunk=2, prefill_chunks_per_step=1)
    res = eng.result(eng.submit([5, 3, 9], max_new_tokens=10), timeout_s=120)
    assert res.status == "completed"
    assert res.tokens == free[:4]
    assert eng.pool.free_count == eng.pool.max_slots
    eng.pool.assert_block_invariants()


def test_chunked_prefill_deadline_eviction(compiled):
    """A request whose deadline expires MID-CHUNKED-PREFILL times out
    with zero tokens and returns every block it had bound."""
    clock = FakeClock()
    eng = _paged_engine(compiled, max_slots=1, clock=clock, kv_block_size=4,
                        prefill_chunk=2, prefill_chunks_per_step=1)
    busy = eng.submit([1, 2], max_new_tokens=40)
    doomed = eng.submit([3, 4, 5, 6, 7, 8], max_new_tokens=5, timeout_s=2.0)
    for _ in range(3):
        eng.step()
    clock.advance(5.0)  # doomed expires while queued behind the busy slot
    eng.run_until_drained()
    assert eng.result(doomed, timeout_s=10).status == "timeout"
    assert eng.result(busy, timeout_s=10).status == "completed"
    assert eng.pool.free_count == eng.pool.max_slots
    eng.pool.assert_block_invariants()


def test_deadline_eviction_mid_prefill_returns_blocks(compiled):
    """Expiry of a PARKED mid-prefill slot (chunk budget starves it
    while decode lanes run) releases the slot and its blocks."""
    clock = FakeClock()
    eng = _paged_engine(compiled, max_slots=2, clock=clock, kv_block_size=4,
                        prefill_chunk=1, prefill_chunks_per_step=1)
    busy = eng.submit([1, 2], max_new_tokens=30)
    eng.step()  # busy admits and starts decoding
    doomed = eng.submit([3, 4, 5, 6, 7, 8], max_new_tokens=5, timeout_s=1.0)
    eng.step()  # doomed claims a slot; 1-chunk budget leaves it parked
    assert eng.scheduler._prefilling  # mid-prefill, blocks bound
    held = eng.pool.blocks_in_use
    assert held > 0
    clock.advance(3.0)
    eng.run_until_drained()
    assert eng.result(doomed, timeout_s=10).status == "timeout"
    assert eng.result(busy, timeout_s=10).status == "completed"
    assert eng.pool.free_count == eng.pool.max_slots
    eng.pool.assert_block_invariants()


def test_prefix_hit_skips_prefill_and_stays_identical(compiled):
    """Back-to-back conversations sharing a full-block system prompt:
    the later ones admit off resident blocks (hit counters move, saved
    tokens accrue) and still emit oracle tokens."""
    sys_prompt = [7, 8, 9, 10]
    prompts = [sys_prompt + [1, 2], sys_prompt + [3, 4, 5], sys_prompt + [1, 2]]
    eng = _paged_engine(compiled, max_slots=2, kv_block_size=4)
    outs = []
    for p in prompts:  # sequential turns → later ones can share
        outs.append(eng.result(eng.submit(p, max_new_tokens=6),
                               timeout_s=120).tokens)
    for p, tokens in zip(prompts, outs):
        assert tokens == _per_row(compiled, p, 6)
    st = eng.stats()
    assert st["prefix_hits"] == 2 and st["prefix_lookups"] == 3
    assert st["prefix_tokens_saved"] == 8
    assert st["prefix_hit_rate"] == pytest.approx(2 / 3)
    eng.pool.assert_block_invariants()


def test_paged_stats_and_load_signals(compiled):
    eng = _paged_engine(compiled, kv_block_size=4)
    _serve_all(eng, [[5, 3, 9]], max_new_tokens=4)
    st = eng.stats()
    assert st["kv_blocks_total"] == eng.pool.num_blocks
    assert 0 <= st["kv_blocks_free"] <= st["kv_blocks_total"]
    sig = eng.load.snapshot()["signals"]
    assert sig["kv_blocks_total"] == eng.pool.num_blocks
    assert sig["kv_free_frac"] == pytest.approx(
        sig["kv_blocks_free"] / sig["kv_blocks_total"])
    assert "prefix_hit_rate" in sig


def test_paged_pool_donation_guard(compiled):
    eng = _paged_engine(compiled, kv_block_size=4)
    eng.submit([5, 3, 9], max_new_tokens=4)
    eng.step()
    stale = eng.pool.cache
    eng.step()  # decode donates the pool; `stale` buffers die
    assert any(leaf.is_deleted()
               for leaf in jax.tree_util.tree_leaves(stale))
    eng.run_until_drained()
    eng.pool.swap(stale)
    with pytest.raises(DonatedBufferError):
        _ = eng.pool.cache


def test_paged_shard_serving_refuses_warm_engine(compiled):
    eng = _paged_engine(compiled, kv_block_size=4)
    _serve_all(eng, [[5, 3, 9]], max_new_tokens=2)
    with pytest.raises(RuntimeError, match="before the first request"):
        eng.shard_serving(None)
