"""TransformerLM autoregressive sampling (KV-cache incremental decode).

The reference has no generative models (SURVEY.md §5.7); this pins the
inference half of the long-context story: the cached decode path is
numerically the full forward, and a trained model's samples follow the
structure it learned.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu.api.compile import CompiledModel
from elephas_tpu.models import get_model
from elephas_tpu.models.transformer import generate

VOCAB, SEQ = 64, 32


def _compiled(attention="dense", **kw):
    return CompiledModel(
        get_model(
            "transformer_lm", vocab_size=VOCAB, d_model=32, num_heads=4,
            num_layers=2, max_seq_len=SEQ, attention=attention, **kw,
        ),
        optimizer={"name": "adam", "learning_rate": 3e-3},
        loss="sparse_categorical_crossentropy",
        metrics=[],
        input_shape=(SEQ,),
        input_dtype=jnp.int32,
        seed=0,
    )


def test_incremental_decode_matches_full_forward():
    """Per-position logits from the KV-cache path equal the ordinary
    full-context forward — the cache is an optimization, never math."""
    compiled = _compiled()
    rng = np.random.default_rng(0)
    tokens = jnp.asarray(
        rng.integers(0, VOCAB, size=(2, SEQ), dtype=np.int32)
    )
    full = compiled.apply_eval(compiled.params, {}, tokens)

    module = dataclasses.replace(compiled.module, decode=True)
    cache = module.init(
        jax.random.PRNGKey(0), jnp.zeros((2, SEQ), jnp.int32)
    )["cache"]
    steps = []
    for t in range(SEQ):
        logits, mutated = module.apply(
            {"params": compiled.params, "cache": cache},
            tokens[:, t:t + 1],
            mutable=["cache"],
        )
        cache = mutated["cache"]
        steps.append(np.asarray(logits[:, 0]))
    incremental = np.stack(steps, axis=1)
    np.testing.assert_allclose(
        incremental, np.asarray(full), rtol=2e-4, atol=2e-4
    )

    # Batched PREFILL (one apply over the whole prompt) is the same math
    # as both of the above — it's what generate() runs over the prompt.
    cache2 = module.init(
        jax.random.PRNGKey(0), jnp.zeros((2, SEQ), jnp.int32)
    )["cache"]
    prefill, _ = module.apply(
        {"params": compiled.params, "cache": cache2}, tokens,
        mutable=["cache"],
    )
    np.testing.assert_allclose(
        np.asarray(prefill), np.asarray(full), rtol=2e-4, atol=2e-4
    )


def test_generate_greedy_follows_learned_recurrence():
    """Train on token[i] = token[i-1] + token[i-2] (mod vocab), then
    greedy-generate: the continuation must follow the recurrence for
    most positions — proof the sampler really runs the trained model."""
    from elephas_tpu.engine.step import init_train_state, make_train_step

    compiled = _compiled()
    rng = np.random.default_rng(1)
    base = rng.integers(0, VOCAB, size=(16, SEQ + 1)).astype(np.int32)
    for i in range(2, SEQ + 1):
        base[:, i] = (base[:, i - 1] + base[:, i - 2]) % VOCAB

    step = jax.jit(make_train_step(compiled))
    state = init_train_state(compiled)
    x, t = jnp.asarray(base[:, :-1]), jnp.asarray(base[:, 1:])
    for _ in range(60):
        state, metrics = step(state, x, t)
    assert float(metrics["loss"]) < 1.0  # learned the recurrence

    # Prompt with TRAINING-ROW prefixes: a 16-row toy fit memorizes its
    # corpus rather than abstracting mod-64 addition, so generalization
    # to arbitrary seeds is not what this pins — the sampler faithfully
    # continuing sequences the model knows is.
    prompt = base[:3, :4].copy()
    out = generate(compiled, prompt, max_new_tokens=12, params=state.params)
    assert out.shape == (3, 16)
    assert np.array_equal(out[:, :4], prompt)  # prompt preserved
    want_hits = 0
    total = 0
    for row in out:
        for i in range(4, len(row)):
            want_hits += int(row[i] == (row[i - 1] + row[i - 2]) % VOCAB)
            total += 1
    assert want_hits / total > 0.7, f"{want_hits}/{total} follow the recurrence"


def test_generate_temperature_and_determinism():
    compiled = _compiled()
    prompt = np.zeros((2, 3), dtype=np.int32)
    a = generate(compiled, prompt, max_new_tokens=5, temperature=1.0, seed=4)
    b = generate(compiled, prompt, max_new_tokens=5, temperature=1.0, seed=4)
    c = generate(compiled, prompt, max_new_tokens=5, temperature=1.0, seed=5)
    np.testing.assert_array_equal(a, b)  # same seed, same sample
    assert a.shape == c.shape == (2, 8)


def test_generate_bf16_model_and_namespace_export():
    """bf16-dtype models decode through the cache path (the caches
    inherit the model dtype), and ``generate`` is importable from the
    models namespace."""
    from elephas_tpu.models import generate as ns_generate

    compiled = _compiled(dtype=jnp.bfloat16)
    out = ns_generate(
        compiled, np.zeros((2, 3), np.int32), max_new_tokens=4
    )
    assert out.shape == (2, 7)
    assert (out >= 0).all() and (out < VOCAB).all()


def test_generate_top_k_one_is_greedy():
    """top_k=1 collapses categorical sampling onto the argmax at ANY
    temperature — the truncation really gates what can be drawn."""
    compiled = _compiled()
    prompt = np.arange(6, dtype=np.int32).reshape(2, 3)
    greedy = generate(compiled, prompt, max_new_tokens=6, temperature=0.0)
    topk1 = generate(
        compiled, prompt, max_new_tokens=6, temperature=2.0, top_k=1, seed=9
    )
    np.testing.assert_array_equal(greedy, topk1)
    with pytest.raises(ValueError, match="top_k"):
        generate(compiled, prompt, max_new_tokens=2, top_k=VOCAB + 1)


def test_generate_validates_inputs():
    compiled = _compiled()
    with pytest.raises(ValueError, match="exceeds max_seq_len"):
        generate(compiled, np.zeros((1, 4), np.int32), max_new_tokens=SEQ)
    with pytest.raises(ValueError, match="prompt must be"):
        generate(compiled, np.zeros((4,), np.int32), max_new_tokens=2)

    mlp = CompiledModel(
        get_model("mlp", features=(8,), num_classes=4),
        optimizer="sgd", loss="categorical_crossentropy", metrics=[],
        input_shape=(6,),
    )
    with pytest.raises(TypeError, match="TransformerLM"):
        generate(mlp, np.zeros((1, 2), np.int32), max_new_tokens=2)


def test_generate_from_sequence_parallel_trained_model():
    """A model TRAINED with attention='ring' under dp×sp samples through
    the cache path unchanged (identical parameter tree) — train to low
    loss on the recurrence, then generate follows it."""
    from elephas_tpu.parallel.mesh import build_mesh
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer

    compiled = _compiled("ring")
    mesh = build_mesh(num_data=2, num_seq=4)
    rng = np.random.default_rng(2)
    base = rng.integers(0, VOCAB, size=(16, SEQ + 1)).astype(np.int32)
    for i in range(2, SEQ + 1):
        base[:, i] = (base[:, i - 1] + base[:, i - 2]) % VOCAB
    trainer = SeqParallelTrainer(compiled, mesh)
    state, history = trainer.fit(base, epochs=60, batch_size=16)
    assert history["loss"][-1] < 1.0

    prompt = base[:1, :4].copy()  # training-row prefix (memorized corpus)
    out = generate(compiled, prompt, max_new_tokens=10, params=state.params)
    hits = sum(
        int(out[0, i] == (out[0, i - 1] + out[0, i - 2]) % VOCAB)
        for i in range(4, out.shape[1])
    )
    assert hits / (out.shape[1] - 4) > 0.7
