"""RWLock discipline tests (reference §5.2: async locks, hogwild doesn't)."""

import threading
import time

import pytest

from elephas_tpu.utils.rwlock import NullLock, RWLock


def test_multiple_readers():
    lock = RWLock()
    active = []

    def reader():
        with lock.reading():
            active.append(1)
            time.sleep(0.05)
            active.pop()

    threads = [threading.Thread(target=reader) for _ in range(4)]
    start = time.monotonic()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # Readers overlap: 4 × 50ms must finish well under 200ms serial time.
    assert time.monotonic() - start < 0.15


def test_writer_excludes_readers():
    lock = RWLock()
    log = []

    def writer():
        with lock.writing():
            log.append("w_start")
            time.sleep(0.05)
            log.append("w_end")

    def reader():
        time.sleep(0.01)  # let the writer in first
        with lock.reading():
            log.append("r")

    tw = threading.Thread(target=writer)
    tr = threading.Thread(target=reader)
    tw.start()
    tr.start()
    tw.join()
    tr.join()
    assert log == ["w_start", "w_end", "r"]


def test_writer_preference_no_starvation():
    """Once a writer waits, fresh readers must queue behind it."""
    lock = RWLock()
    order = []
    lock.acquire_read()

    def writer():
        lock.acquire_write()
        order.append("w")
        lock.release()

    def late_reader():
        time.sleep(0.02)  # after the writer queued
        lock.acquire_read()
        order.append("r")
        lock.release()

    tw = threading.Thread(target=writer)
    tr = threading.Thread(target=late_reader)
    tw.start()
    time.sleep(0.01)
    tr.start()
    time.sleep(0.02)
    lock.release()  # release initial read — writer should go first
    tw.join()
    tr.join()
    assert order == ["w", "r"]


def test_release_without_hold_raises():
    with pytest.raises(RuntimeError):
        RWLock().release()


def test_null_lock_is_noop():
    lock = NullLock()
    with lock.reading():
        with lock.writing():
            pass  # no deadlock, no error
