"""Wire framing + master discovery (reference utils/sockets.py tests)."""

import socket
import threading

import numpy as np

from elephas_tpu.utils import sockets as su


def test_determine_master_format():
    master = su.determine_master(4000)
    host, port = master.rsplit(":", 1)
    assert port == "4000"
    assert host  # resolvable-ish string


def test_send_receive_roundtrip():
    a, b = socket.socketpair()
    payload = {"w": np.arange(10.0), "tag": "delta", "nested": [np.ones((3, 2))]}
    out = {}

    def rx():
        out["obj"] = su.receive(b)

    t = threading.Thread(target=rx)
    t.start()
    su.send(a, payload)
    t.join()
    np.testing.assert_allclose(out["obj"]["w"], payload["w"])
    np.testing.assert_allclose(out["obj"]["nested"][0], np.ones((3, 2)))
    a.close()
    b.close()


def test_send_receive_large_frame():
    """Frames larger than one recv() chunk reassemble correctly."""
    a, b = socket.socketpair()
    big = np.random.default_rng(0).normal(size=(512, 1024)).astype(np.float32)
    received = {}

    def rx():
        received["arr"] = su.receive(b)

    t = threading.Thread(target=rx)
    t.start()
    su.send(a, big)
    t.join()
    np.testing.assert_array_equal(received["arr"], big)
    a.close()
    b.close()
