"""Streaming sync-fit tests: bounded-residency pipeline (VERDICT r1 #5)."""

import jax
import numpy as np
import pytest

from elephas_tpu import SparkModel, compile_model, to_simple_rdd
from elephas_tpu.models import get_model

from conftest import make_blobs

NUM_CLASSES, DIM = 4, 20


def fresh_model():
    return compile_model(
        get_model("mlp", features=(32,), num_classes=NUM_CLASSES),
        optimizer={"name": "adam", "learning_rate": 0.01},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(DIM,),
    )


@pytest.mark.parametrize("frequency", ["batch", "epoch"])
def test_streaming_converges(frequency):
    x, y = make_blobs(n=1024, num_classes=NUM_CLASSES, dim=DIM, seed=5)
    model = SparkModel(fresh_model(), mode="synchronous", frequency=frequency, num_workers=4)
    # 1024 rows / (4 shards * 16) = 16 global batches; stream 3 at a time
    # (ragged last chunk exercises the retrace path).
    history = model.fit(
        to_simple_rdd(None, x, y, 4), epochs=4, batch_size=16,
        validation_split=0.1, stream_batches=3,
    )
    assert history["acc"][-1] > 0.8
    assert len(history["val_acc"]) == 4
    assert model.evaluate(x, y)["acc"] > 0.8


def test_streaming_matches_resident_quality():
    x, y = make_blobs(n=512, num_classes=NUM_CLASSES, dim=DIM, seed=6)
    resident = SparkModel(fresh_model(), mode="synchronous", frequency="batch", num_workers=4)
    h_res = resident.fit(to_simple_rdd(None, x, y, 4), epochs=3, batch_size=16)
    streamed = SparkModel(fresh_model(), mode="synchronous", frequency="batch", num_workers=4)
    h_str = streamed.fit(
        to_simple_rdd(None, x, y, 4), epochs=3, batch_size=16, stream_batches=2
    )
    # Different shuffle orders, same algorithm: both converge to the
    # same statistical quality (loose reference-style assertion).
    assert abs(h_res["acc"][-1] - h_str["acc"][-1]) < 0.1
    assert h_str["acc"][-1] > 0.85


def test_streaming_residency_is_bounded(monkeypatch):
    """The device never holds more than ~2 chunks of data at once."""
    x, y = make_blobs(n=2048, num_classes=NUM_CLASSES, dim=DIM, seed=7)
    put_sizes = []
    real_put = jax.device_put

    def counting_put(arr, sharding=None, **kw):
        if hasattr(arr, "nbytes"):
            put_sizes.append(arr.nbytes)
        return real_put(arr, sharding, **kw)

    monkeypatch.setattr(jax, "device_put", counting_put)
    model = SparkModel(fresh_model(), mode="synchronous", frequency="batch", num_workers=4)
    model.fit(to_simple_rdd(None, x, y, 4), epochs=1, batch_size=16, stream_batches=4)
    # 2048 rows * 20 f32 features = 164KB total; a streamed chunk is
    # 4 batches * 64 rows * 80B = 20KB. No single transfer approaches the
    # full epoch stack.
    full_epoch_bytes = x.nbytes + y.nbytes
    assert put_sizes, "no transfers recorded"
    assert max(put_sizes) < full_epoch_bytes / 3


def test_streaming_rejects_fit_parity_mode():
    x, y = make_blobs(n=256, num_classes=NUM_CLASSES, dim=DIM, seed=8)
    model = SparkModel(fresh_model(), mode="synchronous", frequency="fit", num_workers=4)
    with pytest.raises(ValueError, match="stream"):
        model.fit(to_simple_rdd(None, x, y, 4), epochs=1, batch_size=16, stream_batches=2)


def test_streaming_async_supported_and_validates():
    """r5: async/hogwild accept stream_batches (the bounded-HBM worker
    pipeline — convergence matrix in test_spark_model.py); a nonsense
    chunk size still fails loudly at construction."""
    import pytest as _pytest

    from elephas_tpu.engine.async_engine import AsyncTrainer
    from elephas_tpu.parallel.mesh import build_mesh
    from elephas_tpu.api.compile import CompiledModel
    from elephas_tpu.models import get_model

    net = CompiledModel(
        get_model("mlp", features=(8,), num_classes=NUM_CLASSES),
        optimizer="sgd", loss="categorical_crossentropy", metrics=[],
        input_shape=(DIM,),
    )
    with _pytest.raises(ValueError, match="stream_batches"):
        AsyncTrainer(net, build_mesh(num_data=2), stream_batches=0)

    x, y = make_blobs(n=256, num_classes=NUM_CLASSES, dim=DIM, seed=8)
    model = SparkModel(fresh_model(), mode="asynchronous", num_workers=4)
    history = model.fit(
        to_simple_rdd(None, x, y, 4), epochs=2, batch_size=16,
        stream_batches=2,
    )
    assert len(history["loss"]) == 2
