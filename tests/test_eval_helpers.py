"""Units for the shared eval helpers in ``engine/step.py``:
``weighted_mean_over_chunks`` (exact weighted metric mean, reference
§3.5 semantics) and ``DeviceEvalCache`` (small identity-keyed LRU device
cache with a size bound — serving repeated per-epoch validation without
per-epoch re-uploads, and streaming for oversized sets)."""

import numpy as np

from elephas_tpu.engine import step as step_mod
from elephas_tpu.engine.step import DeviceEvalCache, weighted_mean_over_chunks


def test_weighted_mean_exact_over_ragged_chunks():
    # 10 rows in chunks of 4/4/2; metric = mean of values per chunk.
    values = np.arange(10, dtype=np.float64)
    spans = [(0, 4), (4, 8), (8, 10)]

    def eval_chunk(start, stop):
        return {"m": float(values[start:stop].mean())}

    out = weighted_mean_over_chunks(spans, eval_chunk, 10)
    assert out == {"m": float(values.mean())}


def test_weighted_mean_passes_extra_span_fields():
    spans = [(0, 2, "tag"), (2, 3, "tag2")]
    seen = []

    def eval_chunk(start, stop, tag):
        seen.append(tag)
        return {"m": 1.0}

    assert weighted_mean_over_chunks(spans, eval_chunk, 3) == {"m": 1.0}
    assert seen == ["tag", "tag2"]


def test_device_eval_cache_hits_on_identity_and_rebuilds_on_new_arrays():
    cache = DeviceEvalCache()
    a, b = np.zeros(4), np.ones(4)
    builds = []

    def make():
        builds.append(1)
        return ("built", len(builds))

    first = cache.get((a, b), a.nbytes + b.nbytes, make)
    again = cache.get((a, b), a.nbytes + b.nbytes, make)
    assert first == again == ("built", 1) and len(builds) == 1
    # equal CONTENT but different object ⇒ rebuild (identity semantics:
    # a recycled id with different data must never be served stale)
    a2 = np.zeros(4)
    rebuilt = cache.get((a2, b), a2.nbytes + b.nbytes, make)
    assert rebuilt == ("built", 2)


def test_device_eval_cache_scalar_key_participates():
    cache = DeviceEvalCache()
    a = np.zeros(4)
    builds = []
    cache.get((a, 8), a.nbytes, lambda: builds.append(1))
    cache.get((a, 8), a.nbytes, lambda: builds.append(1))
    cache.get((a, 12), a.nbytes, lambda: builds.append(1))  # usable changed
    assert len(builds) == 2


def test_device_eval_cache_alternating_sets_both_stay_resident():
    """Two validation sets used alternately (estimator split + manual
    evaluate) must each upload exactly once — the r3 one-slot cache
    thrashed silently on this pattern."""
    cache = DeviceEvalCache()
    a, b = np.zeros(4), np.ones(4)
    builds = []

    def make_for(tag):
        def make():
            builds.append(tag)
            return tag

        return make

    for _ in range(3):
        assert cache.get((a,), a.nbytes, make_for("A")) == "A"
        assert cache.get((b,), b.nbytes, make_for("B")) == "B"
    assert builds == ["A", "B"]


def test_device_eval_cache_evicts_least_recently_used():
    cache = DeviceEvalCache(slots=2)
    arrs = [np.full(4, i) for i in range(3)]
    builds = []

    def make_for(i):
        def make():
            builds.append(i)
            return i

        return make

    cache.get((arrs[0],), 4, make_for(0))
    cache.get((arrs[1],), 4, make_for(1))
    cache.get((arrs[0],), 4, make_for(0))  # refresh 0 → 1 is now LRU
    cache.get((arrs[2],), 4, make_for(2))  # evicts 1
    assert cache.get((arrs[0],), 4, make_for(0)) == 0  # still cached
    cache.get((arrs[1],), 4, make_for(1))  # rebuilds
    assert builds == [0, 1, 2, 1]


def test_device_eval_cache_total_bytes_bounded_before_upload(monkeypatch):
    """Cached entries together never exceed the byte budget, and eviction
    happens BEFORE the new set builds (peak pinned memory == budget)."""
    monkeypatch.setattr(step_mod, "_EVAL_CACHE_MAX_BYTES", 100)
    cache = DeviceEvalCache(slots=4)
    a, b = np.zeros(60, dtype=np.uint8), np.zeros(60, dtype=np.uint8)

    def make_checking_budget(tag):
        def make():
            held = sum(e[1] for e in cache._entries)
            assert held + 60 <= 100, "evicted after upload, not before"
            return tag

        return make

    assert cache.get((a,), 60, make_checking_budget("A")) == "A"
    assert cache.get((b,), 60, make_checking_budget("B")) == "B"  # evicts A
    assert [e[2] for e in cache._entries] == ["B"]


def test_device_eval_cache_declines_oversized_sets(monkeypatch):
    monkeypatch.setattr(step_mod, "_EVAL_CACHE_MAX_BYTES", 100)
    cache = DeviceEvalCache()
    big = np.zeros(200, dtype=np.uint8)
    assert cache.get((big,), big.nbytes, lambda: "never") is None
    small = np.zeros(10, dtype=np.uint8)
    assert cache.get((small,), small.nbytes, lambda: "yes") == "yes"
