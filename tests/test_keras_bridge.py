"""Keras-3 (JAX backend) ingestion tests (SURVEY.md §7 hard part 2)."""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np
import pytest

keras = pytest.importorskip("keras")
if keras.backend.backend() != "jax":  # keras already imported with another backend
    pytest.skip("keras backend is not jax in this process", allow_module_level=True)

from elephas_tpu import SparkModel, to_simple_rdd
from elephas_tpu.serialize.keras_bridge import KerasModuleAdapter, from_keras

from conftest import make_blobs


def _keras_mlp(compile_it=True):
    model = keras.Sequential(
        [
            keras.layers.Input((12,)),
            keras.layers.Dense(24, activation="relu"),
            keras.layers.Dropout(0.1),
            keras.layers.Dense(3),
        ]
    )
    if compile_it:
        model.compile(optimizer=keras.optimizers.Adam(0.01), loss="categorical_crossentropy")
    return model


def test_from_keras_reads_compile_config():
    compiled = from_keras(_keras_mlp())
    assert compiled.loss_name == "categorical_crossentropy"
    assert compiled.optimizer_config["name"] == "adam"
    assert compiled.optimizer_config["learning_rate"] == pytest.approx(0.01)
    assert compiled.count_params() == 12 * 24 + 24 + 24 * 3 + 3


def test_from_keras_uncompiled_requires_explicit_args():
    model = _keras_mlp(compile_it=False)
    with pytest.raises(ValueError, match="not compiled"):
        from_keras(model)
    compiled = from_keras(model, optimizer="sgd", loss="categorical_crossentropy")
    assert compiled.optimizer_config["name"] == "sgd"


def test_keras_model_trains_through_spark_model():
    x, y = make_blobs(n=384, num_classes=3, dim=12, seed=9)
    compiled = from_keras(_keras_mlp())
    model = SparkModel(compiled, mode="synchronous", frequency="batch", num_workers=4)
    history = model.fit(to_simple_rdd(None, x, y, 4), epochs=3, batch_size=16)
    assert history["acc"][-1] > 0.8
    assert model.evaluate(x, y)["acc"] > 0.8
    preds = model.predict(x[:5])
    assert preds.shape == (5, 3)


def test_keras_model_async_mode():
    x, y = make_blobs(n=256, num_classes=3, dim=12, seed=10)
    compiled = from_keras(_keras_mlp())
    model = SparkModel(compiled, mode="hogwild", frequency="epoch", num_workers=2)
    model.fit(to_simple_rdd(None, x, y, 2), epochs=3, batch_size=16)
    assert model.evaluate(x, y)["acc"] > 0.8


def test_adapter_rejects_unbuilt_model():
    model = keras.Sequential([keras.layers.Dense(4)])
    with pytest.raises(ValueError, match="build"):
        KerasModuleAdapter(model)

def test_softmax_output_maps_to_prob_loss_and_trains():
    # Reference-style model: softmax output + from_logits=False loss must
    # NOT be mapped onto the logit loss (double softmax) — ADVICE r1.
    x, y = make_blobs(n=384, num_classes=3, dim=12, seed=11)
    model = keras.Sequential(
        [
            keras.layers.Input((12,)),
            keras.layers.Dense(24, activation="relu"),
            keras.layers.Dense(3, activation="softmax"),
        ]
    )
    model.compile(optimizer=keras.optimizers.Adam(0.01), loss="categorical_crossentropy")
    compiled = from_keras(model)
    assert compiled.loss_name == "categorical_crossentropy_probs"
    sm = SparkModel(compiled, mode="synchronous", frequency="batch", num_workers=4)
    history = sm.fit(to_simple_rdd(None, x, y, 4), epochs=3, batch_size=16)
    assert history["acc"][-1] > 0.8


def test_sigmoid_binary_maps_to_prob_loss_and_metric():
    model = keras.Sequential(
        [
            keras.layers.Input((8,)),
            keras.layers.Dense(16, activation="relu"),
            keras.layers.Dense(1, activation="sigmoid"),
        ]
    )
    model.compile(optimizer=keras.optimizers.Adam(0.02), loss="binary_crossentropy")
    compiled = from_keras(model)
    assert compiled.loss_name == "binary_crossentropy_probs"
    assert "binary_accuracy_probs" in compiled.metric_names
    rng = np.random.default_rng(3)
    x = rng.normal(size=(256, 8)).astype(np.float32)
    w = rng.normal(size=(8, 1)).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)
    sm = SparkModel(compiled, mode="synchronous", frequency="batch", num_workers=4)
    history = sm.fit(to_simple_rdd(None, x, y, 4), epochs=10, batch_size=16)
    assert history["binary_accuracy_probs"][-1] > 0.8


def test_from_logits_true_keeps_logit_loss():
    model = _keras_mlp(compile_it=False)
    model.compile(
        optimizer=keras.optimizers.Adam(0.01),
        loss=keras.losses.CategoricalCrossentropy(from_logits=True),
    )
    compiled = from_keras(model)
    assert compiled.loss_name == "categorical_crossentropy"


def test_mismatched_activation_loss_pair_raises():
    model = keras.Sequential(
        [
            keras.layers.Input((8,)),
            keras.layers.Dense(3, activation="softmax"),
        ]
    )
    model.compile(optimizer="adam", loss="binary_crossentropy")
    with pytest.raises(ValueError, match="cannot map"):
        from_keras(model)


def test_standalone_softmax_layer_maps_to_prob_loss():
    model = keras.Sequential(
        [keras.layers.Input((6,)), keras.layers.Dense(3), keras.layers.Softmax()]
    )
    model.compile(optimizer="adam", loss="categorical_crossentropy")
    compiled = from_keras(model)
    assert compiled.loss_name == "categorical_crossentropy_probs"


def test_spark_model_accepts_compiled_keras_directly():
    """Reference drop-in: ``SparkModel(compiled_keras_model, ...)`` must
    work without an explicit from_keras/compile_model wrap (the
    reference's SparkModel takes the user's compiled Keras model)."""
    x, y = make_blobs(n=256, num_classes=3, dim=12, seed=5)
    model = SparkModel(_keras_mlp(), mode="synchronous", frequency="epoch",
                       num_workers=2)
    history = model.fit(to_simple_rdd(None, x, y, 2), epochs=3, batch_size=16)
    assert history["acc"][-1] > 0.8
    preds = model.predict(x[:32])
    assert preds.shape == (32, 3)


def test_spark_model_uncompiled_keras_raises_actionably():
    with pytest.raises(ValueError, match="not compiled"):
        SparkModel(_keras_mlp(compile_it=False))


def test_keras_backed_save_load_roundtrip(tmp_path):
    """SparkModel.save/load_spark_model round-trips Keras-backed models
    (arch pickled via Keras-3's own reduce; trained weights + optimizer
    config carried in the payload — reference save/load semantics)."""
    import os

    from elephas_tpu import load_spark_model

    x, y = make_blobs(n=192, num_classes=3, dim=12, seed=6)
    model = SparkModel(_keras_mlp(), mode="synchronous", frequency="epoch",
                       num_workers=2)
    model.fit(to_simple_rdd(None, x, y, 2), epochs=2, batch_size=16)
    path = os.path.join(tmp_path, "keras_model.pkl")
    model.save(path)
    loaded = load_spark_model(path)
    np.testing.assert_allclose(
        loaded.predict(x[:16]), model.predict(x[:16]), rtol=1e-5
    )


def test_keras_lr_schedules_map_to_optax():
    """Keras LearningRateSchedule objects carry over as serializable
    schedule configs (previously silently flattened to the step-0 lr)."""
    from elephas_tpu.api.compile import resolve_schedule
    from elephas_tpu.serialize.keras_bridge import _optimizer_from_keras

    sched = keras.optimizers.schedules.ExponentialDecay(
        0.1, decay_steps=100, decay_rate=0.5
    )
    cfg = _optimizer_from_keras(keras.optimizers.SGD(learning_rate=sched))
    assert cfg["learning_rate"]["schedule"] == "exponential_decay"
    fn = resolve_schedule(cfg["learning_rate"])
    np.testing.assert_allclose(float(fn(100)), 0.05, rtol=1e-6)

    pw = keras.optimizers.schedules.PiecewiseConstantDecay(
        [100, 200], [0.1, 0.01, 0.001]
    )
    cfg = _optimizer_from_keras(keras.optimizers.SGD(learning_rate=pw))
    fn = resolve_schedule(cfg["learning_rate"])
    np.testing.assert_allclose(float(fn(150)), 0.01, rtol=1e-5)
    np.testing.assert_allclose(float(fn(250)), 0.001, rtol=1e-5)
    # AT each boundary Keras keeps the OLD value (switch happens at
    # boundary+1) — probe both sides exactly against Keras itself.
    for step in (99, 100, 101, 200, 201):
        np.testing.assert_allclose(
            float(fn(step)), float(pw(step)), rtol=1e-5,
            err_msg=f"piecewise mismatch vs Keras at step {step}",
        )


def test_dict_lr_without_schedule_key_raises_value_error():
    from elephas_tpu.api.compile import resolve_schedule

    with pytest.raises(ValueError, match="schedule"):
        resolve_schedule({"init_value": 0.1})


def test_schedule_config_trains_and_serializes(tmp_path):
    """A dict-lr optimizer config flows through compile, fit, and the
    model_to_dict round-trip (schedule configs are plain JSON-able)."""
    import os

    from elephas_tpu import SparkModel, compile_model, load_spark_model, to_simple_rdd
    from elephas_tpu.models import get_model

    x, y = make_blobs(n=192, num_classes=3, dim=12, seed=8)
    net = compile_model(
        get_model("mlp", features=(16,), num_classes=3),
        optimizer={
            "name": "sgd",
            "learning_rate": {
                "schedule": "exponential_decay",
                "init_value": 0.1,
                "transition_steps": 50,
                "decay_rate": 0.9,
            },
        },
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(12,),
    )
    model = SparkModel(net, mode="synchronous", frequency="epoch", num_workers=2)
    history = model.fit(to_simple_rdd(None, x, y, 2), epochs=3, batch_size=16)
    assert history["acc"][-1] > 0.8
    path = os.path.join(tmp_path, "sched.pkl")
    model.save(path)
    loaded = load_spark_model(path)
    np.testing.assert_allclose(
        loaded.predict(x[:16]), model.predict(x[:16]), rtol=1e-5
    )


def test_warmup_cosine_matches_keras_pointwise():
    """Keras CosineDecay-with-warmup and the mapped optax schedule agree
    at probe steps: warmup ramps FROM initial_learning_rate, and optax's
    decay_steps is the TOTAL length including warmup."""
    from elephas_tpu.api.compile import resolve_schedule
    from elephas_tpu.serialize.keras_bridge import _optimizer_from_keras

    sched = keras.optimizers.schedules.CosineDecay(
        0.01, decay_steps=200, warmup_target=0.1, warmup_steps=50
    )
    cfg = _optimizer_from_keras(keras.optimizers.Adam(learning_rate=sched))
    fn = resolve_schedule(cfg["learning_rate"])
    for step in (0, 25, 50, 150, 250):
        np.testing.assert_allclose(
            float(fn(step)), float(sched(step)), atol=5e-3
        )
