"""Keras-3 (JAX backend) ingestion tests (SURVEY.md §7 hard part 2)."""

import os

os.environ.setdefault("KERAS_BACKEND", "jax")

import numpy as np
import pytest

keras = pytest.importorskip("keras")
if keras.backend.backend() != "jax":  # keras already imported with another backend
    pytest.skip("keras backend is not jax in this process", allow_module_level=True)

from elephas_tpu import SparkModel, to_simple_rdd
from elephas_tpu.serialize.keras_bridge import KerasModuleAdapter, from_keras

from conftest import make_blobs


def _keras_mlp(compile_it=True):
    model = keras.Sequential(
        [
            keras.layers.Input((12,)),
            keras.layers.Dense(24, activation="relu"),
            keras.layers.Dropout(0.1),
            keras.layers.Dense(3),
        ]
    )
    if compile_it:
        model.compile(optimizer=keras.optimizers.Adam(0.01), loss="categorical_crossentropy")
    return model


def test_from_keras_reads_compile_config():
    compiled = from_keras(_keras_mlp())
    assert compiled.loss_name == "categorical_crossentropy"
    assert compiled.optimizer_config["name"] == "adam"
    assert compiled.optimizer_config["learning_rate"] == pytest.approx(0.01)
    assert compiled.count_params() == 12 * 24 + 24 + 24 * 3 + 3


def test_from_keras_uncompiled_requires_explicit_args():
    model = _keras_mlp(compile_it=False)
    with pytest.raises(ValueError, match="not compiled"):
        from_keras(model)
    compiled = from_keras(model, optimizer="sgd", loss="categorical_crossentropy")
    assert compiled.optimizer_config["name"] == "sgd"


def test_keras_model_trains_through_spark_model():
    x, y = make_blobs(n=384, num_classes=3, dim=12, seed=9)
    compiled = from_keras(_keras_mlp())
    model = SparkModel(compiled, mode="synchronous", frequency="batch", num_workers=4)
    history = model.fit(to_simple_rdd(None, x, y, 4), epochs=3, batch_size=16)
    assert history["acc"][-1] > 0.8
    assert model.evaluate(x, y)["acc"] > 0.8
    preds = model.predict(x[:5])
    assert preds.shape == (5, 3)


def test_keras_model_async_mode():
    x, y = make_blobs(n=256, num_classes=3, dim=12, seed=10)
    compiled = from_keras(_keras_mlp())
    model = SparkModel(compiled, mode="hogwild", frequency="epoch", num_workers=2)
    model.fit(to_simple_rdd(None, x, y, 2), epochs=3, batch_size=16)
    assert model.evaluate(x, y)["acc"] > 0.8


def test_adapter_rejects_unbuilt_model():
    model = keras.Sequential([keras.layers.Dense(4)])
    with pytest.raises(ValueError, match="build"):
        KerasModuleAdapter(model)
