"""Property tests for the packed PS wire codec (parameter/wire.py).

The codec is the PS hot path's foundation: every pull/push crosses it,
so round-trip fidelity (exact bytes for the unquantized path, bounded
error for quantized deltas), structure preservation (including empty
subtrees, which path-list encodings silently drop), and loud failure on
malformed frames are all tier-1 invariants.
"""

import json
import struct

import numpy as np
import pytest

import jax

from elephas_tpu.parameter import wire
from elephas_tpu.utils.sockets import MAGIC_NOTMOD, MAGIC_TREE


def _roundtrip(tree, **encode_kw):
    frames = wire.encode_tree(tree, **encode_kw)
    return wire.decode(frames.tobytes())


def _assert_trees_equal(got, want):
    jax.tree_util.tree_all(jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(a, b), got, want))
    assert jax.tree_util.tree_structure(got) == \
        jax.tree_util.tree_structure(want)


# -- round trips --------------------------------------------------------------


@pytest.mark.parametrize("dtype", ["float32", "float64", "int32", "uint8"])
def test_roundtrip_exact_per_dtype(dtype):
    rng = np.random.default_rng(0)
    tree = {
        "dense": {"kernel": rng.normal(size=(17, 5)).astype(dtype),
                  "bias": rng.normal(size=(5,)).astype(dtype)},
        "stack": [rng.normal(size=(3, 3, 2)).astype(dtype)],
    }
    out = _roundtrip(tree)
    _assert_trees_equal(out.tree, tree)
    for leaf in jax.tree_util.tree_leaves(out.tree):
        assert leaf.dtype == np.dtype(dtype)


def test_roundtrip_bf16_leaves():
    ml_dtypes = pytest.importorskip("ml_dtypes")
    arr = np.arange(24, dtype=np.float32).reshape(4, 6)
    tree = {"w": arr.astype(ml_dtypes.bfloat16)}
    out = _roundtrip(tree)
    assert out.tree["w"].dtype == np.dtype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(
        np.asarray(out.tree["w"], dtype=np.float32),
        np.asarray(tree["w"], dtype=np.float32))


def test_roundtrip_scalars_and_0d():
    tree = {"step": np.int64(7), "lr": np.float32(0.125),
            "zero_d": np.array(3.0, dtype=np.float32)}
    out = _roundtrip(tree).tree
    assert int(out["step"]) == 7
    assert float(out["lr"]) == 0.125
    assert np.shape(out["zero_d"]) == ()


def test_roundtrip_empty_subtrees_and_none():
    """The skeleton must carry structure pickle carries: empty dicts,
    empty lists, None leaves — a path-list encoding would collapse
    ``{"a": {}}`` into ``{}``."""
    tree = {"a": {}, "b": [], "c": None,
            "d": (np.ones((2,), np.float32), {"nested_empty": {}})}
    out = _roundtrip(tree).tree
    assert out["a"] == {}
    assert out["b"] == []
    assert out["c"] is None
    assert isinstance(out["d"], tuple)
    assert out["d"][1] == {"nested_empty": {}}
    np.testing.assert_array_equal(out["d"][0], np.ones((2,), np.float32))


def test_roundtrip_zero_length_leaf():
    out = _roundtrip({"empty": np.zeros((0, 4), np.float32)}).tree
    assert out["empty"].shape == (0, 4)


def test_version_travels_in_header():
    frames = wire.encode_tree({"w": np.ones(3, np.float32)}, version=41)
    assert wire.decode(frames.tobytes()).version == 41
    assert wire.decode(
        wire.encode_tree({"w": np.ones(3, np.float32)}).tobytes()
    ).version is None


def test_decode_is_zero_copy_views():
    buf = wire.encode_tree({"w": np.arange(8, dtype=np.float32)}).tobytes()
    leaf = wire.decode(buf).tree["w"]
    assert not leaf.flags.writeable  # frombuffer view of the frame
    assert leaf.base is not None


def test_payload_is_64b_aligned():
    frames = wire.encode_tree({
        "a": np.ones((3,), np.uint8),  # 3B leaf forces inter-leaf pad
        "b": np.ones((4,), np.float32),
    })
    buf = frames.tobytes()
    (hlen,) = struct.unpack_from("!I", buf, 4)
    header = json.loads(buf[8:8 + hlen])
    assert (8 + hlen) % 64 == 0
    for _, _, offset, _, _, _ in header["leaves"]:
        assert offset % 64 == 0


# -- quantization -------------------------------------------------------------


def test_quantize_bf16_halves_bytes_and_bounds_error():
    pytest.importorskip("ml_dtypes")
    rng = np.random.default_rng(1)
    arr = rng.normal(size=(64, 64)).astype(np.float32)
    plain = wire.encode_tree({"w": arr})
    quant = wire.encode_tree({"w": arr}, quantize="bf16")
    assert quant.nbytes < plain.nbytes * 0.75
    out = wire.decode(quant.tobytes()).tree["w"]
    assert out.dtype == np.float32  # restored to the original dtype
    # bf16 keeps f32's exponent: relative error bounded by 2^-8.
    np.testing.assert_allclose(out, arr, rtol=2.0 ** -7, atol=1e-6)


def test_quantize_f16_scales_large_deltas():
    """Per-leaf scaling must keep values that overflow float16 finite."""
    arr = np.array([1.0e6, -2.0e6, 3.5], dtype=np.float32)
    out = wire.decode(
        wire.encode_tree({"w": arr}, quantize="f16").tobytes()).tree["w"]
    assert np.all(np.isfinite(out))
    np.testing.assert_allclose(out, arr, rtol=2e-3, atol=2e-3 * 2.0e6)


def test_quantize_skips_int_and_half_leaves():
    tree = {"counts": np.arange(5, dtype=np.int32),
            "half": np.ones(5, dtype=np.float16)}
    out = wire.decode(
        wire.encode_tree(tree, quantize="bf16").tobytes()).tree
    np.testing.assert_array_equal(out["counts"], tree["counts"])
    assert out["counts"].dtype == np.int32
    assert out["half"].dtype == np.float16


def test_quantize_unknown_mode_raises():
    with pytest.raises(wire.WireFormatError):
        wire.encode_tree({"w": np.ones(3, np.float32)}, quantize="int4")


# -- not-modified frames ------------------------------------------------------


def test_not_modified_is_12_bytes_roundtrip():
    frames = wire.encode_not_modified(123456789)
    buf = frames.tobytes()
    assert len(buf) == 12 and buf.startswith(MAGIC_NOTMOD)
    out = wire.decode(buf)
    assert isinstance(out, wire.NotModified)
    assert out.version == 123456789


def test_decode_payload_rejects_not_modified():
    with pytest.raises(wire.WireFormatError):
        wire.decode_payload(wire.encode_not_modified(1).tobytes())


# -- negotiation & failure modes ----------------------------------------------


def test_is_packed_distinguishes_pickle():
    packed = wire.encode_tree({"w": np.ones(2, np.float32)}).tobytes()
    legacy = wire.encode_pickle({"w": np.ones(2, np.float32)})
    assert wire.is_packed(packed)
    assert wire.is_packed(wire.encode_not_modified(0).tobytes())
    assert not wire.is_packed(legacy)
    assert legacy[:1] == b"\x80"  # protocol>=2 opcode, disjoint from magics


def test_decode_payload_handles_both_codecs():
    tree = {"w": np.arange(6, dtype=np.float32)}
    for body in (wire.encode_tree(tree).tobytes(), wire.encode_pickle(tree)):
        np.testing.assert_array_equal(
            wire.decode_payload(body)["w"], tree["w"])


def test_treedef_mismatch_raises():
    tree = {"w": np.ones(3, np.float32)}
    buf = wire.encode_tree(tree).tobytes()
    wrong = jax.tree_util.tree_structure({"w": 0, "extra": 0})
    with pytest.raises(wire.WireFormatError, match="treedef mismatch"):
        wire.decode(buf, expect_treedef=wrong)
    ok = jax.tree_util.tree_structure(tree)
    assert wire.decode(buf, expect_treedef=ok).tree is not None


def test_unsupported_structures_fall_to_pickle():
    """Non-JSON dict keys and object leaves raise WireFormatError so
    callers can fall back to encode_pickle."""
    with pytest.raises(wire.WireFormatError):
        wire.encode_tree({("tuple", "key"): np.ones(2, np.float32)})
    with pytest.raises(wire.WireFormatError):
        wire.encode_tree({"w": np.array([object()])})


@pytest.mark.parametrize("mangle", [
    lambda b: b[:6],                               # truncated header
    lambda b: b[:len(b) - 8],                      # truncated payload
    lambda b: MAGIC_TREE + b"\x00\x00\x00\x04junk" + b[12:],  # bad JSON
    lambda b: b"WHAT" + b[4:],                     # unknown magic
])
def test_malformed_frames_raise_wire_errors(mangle):
    good = wire.encode_tree({"w": np.arange(32, dtype=np.float32)}).tobytes()
    with pytest.raises(wire.WireFormatError):
        wire.decode(mangle(good))


def test_frames_nbytes_matches_tobytes():
    frames = wire.encode_tree(
        {"a": np.ones((5, 5), np.float32), "b": np.ones(3, np.uint8)})
    assert frames.nbytes == len(frames.tobytes())
