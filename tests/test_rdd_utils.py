"""ShardedDataset / RDD-utils parity tests (reference rdd_utils tests §4)."""

import numpy as np
import pytest

from elephas_tpu.data.rdd import (
    LabeledPoint,
    ShardedDataset,
    encode_label,
    from_labeled_point,
    lp_to_simple_rdd,
    to_labeled_point,
    to_simple_rdd,
)


def test_to_simple_rdd_partitions():
    x = np.arange(100).reshape(100, 1).astype(np.float32)
    y = np.arange(100).astype(np.float32)
    rdd = to_simple_rdd(None, x, y, num_partitions=4)
    assert rdd.getNumPartitions() == 4
    assert rdd.count() == 100
    assert sum(rdd.partition_sizes()) == 100
    # Partition-faithful: concatenating partitions reproduces the data.
    parts = [rdd.partition(i) for i in range(4)]
    np.testing.assert_array_equal(np.concatenate([p[0] for p in parts]), x)


def test_uneven_partitions():
    x = np.arange(10).reshape(10, 1).astype(np.float32)
    y = np.zeros(10, dtype=np.float32)
    rdd = ShardedDataset(x, y, num_partitions=3)
    sizes = rdd.partition_sizes()
    assert sum(sizes) == 10
    assert max(sizes) - min(sizes) <= 1


def test_repartition_and_shuffle():
    x = np.arange(64).reshape(64, 1).astype(np.float32)
    y = np.arange(64).astype(np.float32)
    rdd = ShardedDataset(x, y, 2).repartition(8)
    assert rdd.getNumPartitions() == 8
    shuffled = rdd.shuffle(seed=1)
    assert not np.array_equal(shuffled.features, rdd.features)
    # Pairing preserved under shuffle.
    np.testing.assert_array_equal(shuffled.features[:, 0], shuffled.labels)


def test_even_shards_truncates():
    x = np.arange(10).reshape(10, 1).astype(np.float32)
    rdd = ShardedDataset(x, np.zeros(10), 1)
    fx, fy = rdd.even_shards(4)
    assert len(fx) == 8 and len(fy) == 8


def test_validation_errors():
    x = np.zeros((4, 2))
    with pytest.raises(ValueError):
        ShardedDataset(x, np.zeros(3), 1)  # length mismatch
    with pytest.raises(ValueError):
        ShardedDataset(x, np.zeros(4), 8)  # more partitions than rows


def test_encode_label():
    np.testing.assert_array_equal(encode_label(2, 4), [0, 0, 1, 0])


def test_labeled_point_roundtrip_categorical():
    x = np.random.default_rng(0).normal(size=(20, 3)).astype(np.float32)
    y_int = np.random.default_rng(1).integers(0, 4, size=20)
    y = np.eye(4, dtype=np.float32)[y_int]
    points = to_labeled_point(None, x, y, categorical=True)
    assert isinstance(points[0], LabeledPoint)
    assert points[0].label == float(y_int[0])
    fx, fy = from_labeled_point(points, categorical=True, nb_classes=4)
    np.testing.assert_allclose(fx, x)
    np.testing.assert_array_equal(fy, y)


def test_labeled_point_roundtrip_regression():
    x = np.random.default_rng(0).normal(size=(10, 3)).astype(np.float32)
    y = np.arange(10).astype(np.float32)
    points = to_labeled_point(None, x, y, categorical=False)
    fx, fy = from_labeled_point(points)
    np.testing.assert_allclose(fy, y)


def test_lp_to_simple_rdd():
    x = np.random.default_rng(0).normal(size=(24, 3)).astype(np.float32)
    y_int = np.random.default_rng(1).integers(0, 3, size=24)
    y = np.eye(3, dtype=np.float32)[y_int]
    points = to_labeled_point(None, x, y, categorical=True)
    rdd = lp_to_simple_rdd(points, categorical=True, nb_classes=3, num_partitions=4)
    assert rdd.getNumPartitions() == 4
    np.testing.assert_array_equal(rdd.labels, y)
