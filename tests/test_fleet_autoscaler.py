"""FleetAutoscaler: the pure decision core (replay-stable sequences,
hysteresis, cooldown, clamps, narration) and the router actually
actuating its decisions against live replicas.
"""

import time

import jax.numpy as jnp
import pytest

from elephas_tpu import obs
from elephas_tpu.obs.flight import FlightRecorder
from elephas_tpu.obs.slo import GoodputLedger
from elephas_tpu.serving import (
    FleetAutoscaler,
    InferenceEngine,
    ReplicaSet,
    Router,
)

VOCAB, SEQ = 97, 64


@pytest.fixture(scope="module")
def compiled():
    from elephas_tpu.api.compile import CompiledModel
    from elephas_tpu.models import get_model

    return CompiledModel(
        get_model(
            "transformer_lm", vocab_size=VOCAB, d_model=32, num_heads=4,
            num_layers=2, max_seq_len=SEQ,
        ),
        optimizer={"name": "adam", "learning_rate": 3e-3},
        loss="sparse_categorical_crossentropy",
        metrics=[],
        input_shape=(SEQ,),
        input_dtype=jnp.int32,
        seed=0,
    )


@pytest.fixture()
def flight():
    previous = obs.default_flight_recorder()
    recorder = FlightRecorder(capacity=256)
    obs.set_default_flight_recorder(recorder)
    try:
        yield recorder
    finally:
        obs.set_default_flight_recorder(previous)


def _auto(**kw):
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("up_burn", 1.0)
    kw.setdefault("down_burn", 0.25)
    kw.setdefault("up_after", 2)
    kw.setdefault("down_after", 3)
    kw.setdefault("cooldown_s", 60.0)
    return FleetAutoscaler(**kw)


def _drive(auto, schedule, n0=1):
    """Feed (t, burn) pairs, tracking the simulated replica count the
    way the router would actuate it."""
    n = n0
    out = []
    for t, burn in schedule:
        decision = auto.observe(burn=burn, n_replicas=n, now=t)
        out.append(decision)
        if decision == "up":
            n += 1
        elif decision == "down":
            n -= 1
    return out, n


# -- the pure core ---------------------------------------------------------


def test_decision_sequence_is_replay_stable():
    """The chaos-arm promise: same observation ladder, same decisions —
    twice, exactly, including timestamps."""
    schedule = ([(10.0 * i, 5.0) for i in range(4)]
                + [(40.0 + 30.0 * i, 0.0) for i in range(12)])
    runs = []
    for _ in range(2):
        auto = _auto(max_replicas=3)
        _drive(auto, schedule)
        runs.append([(d["t"], d["direction"], d["replicas"])
                     for d in auto.decisions])
    assert runs[0] == runs[1]
    assert [d[1] for d in runs[0]] == ["up", "down"]
    up_t, down_t = runs[0][0][0], runs[0][1][0]
    assert down_t - up_t >= 60.0  # the cooldown held


def test_streaks_gate_both_directions():
    """One bad observation is a blip: no decision until the streak
    reaches up_after / down_after consecutive breaches."""
    auto = _auto(up_after=3, down_after=2, cooldown_s=0.0)
    assert auto.observe(burn=5.0, n_replicas=1, now=0.0) is None
    assert auto.observe(burn=5.0, n_replicas=1, now=1.0) is None
    assert auto.observe(burn=5.0, n_replicas=1, now=2.0) == "up"
    assert auto.observe(burn=0.0, n_replicas=2, now=3.0) is None
    assert auto.observe(burn=0.0, n_replicas=2, now=4.0) == "down"


def test_hysteresis_band_resets_streaks():
    """Burn hovering between down_burn and up_burn kills both trends —
    the band is what stops threshold flapping."""
    auto = _auto(up_after=2, cooldown_s=0.0)
    auto.observe(burn=5.0, n_replicas=1, now=0.0)
    auto.observe(burn=0.5, n_replicas=1, now=1.0)   # in the dead band
    assert auto.observe(burn=5.0, n_replicas=1, now=2.0) is None
    assert auto.observe(burn=5.0, n_replicas=1, now=3.0) == "up"
    assert auto.snapshot()["up_streak"] == 0


def test_cooldown_blocks_actuation_but_not_streaks():
    auto = _auto(up_after=2, cooldown_s=100.0, max_replicas=8)
    auto.observe(burn=5.0, n_replicas=1, now=0.0)
    assert auto.observe(burn=5.0, n_replicas=1, now=10.0) == "up"
    # Still burning: the streak rebuilds, but nothing fires inside the
    # cooldown window...
    assert auto.observe(burn=5.0, n_replicas=2, now=20.0) is None
    assert auto.observe(burn=5.0, n_replicas=2, now=30.0) is None
    # ...and the first observation past it can fire immediately.
    assert auto.observe(burn=5.0, n_replicas=2, now=111.0) == "up"


def test_min_max_clamps():
    auto = _auto(min_replicas=1, max_replicas=2, up_after=1,
                 down_after=1, cooldown_s=0.0)
    assert auto.observe(burn=5.0, n_replicas=2, now=0.0) is None
    assert auto.observe(burn=0.0, n_replicas=1, now=1.0) is None
    assert auto.observe(burn=5.0, n_replicas=1, now=2.0) == "up"
    assert auto.observe(burn=0.0, n_replicas=2, now=3.0) == "down"


def test_decisions_are_narrated(flight):
    """Every actuation lands as a fleet_scale flight event and a
    fleet_scale_events_total{direction=} tick."""
    family = obs.default_registry().counter(
        "fleet_scale_events_total",
        help="autoscaler decisions actuated, by direction",
        labelnames=("direction",))
    up0 = family.labels(direction="up").value
    auto = _auto(up_after=1, cooldown_s=0.0)
    auto.observe(burn=5.0, n_replicas=1, now=0.0)
    assert family.labels(direction="up").value - up0 == 1
    events = flight.events(kind="fleet_scale")
    assert len(events) == 1
    assert events[0].detail["direction"] == "up"
    assert events[0].detail["replicas"] == 1


def test_policy_validation():
    with pytest.raises(ValueError):
        FleetAutoscaler(min_replicas=0)
    with pytest.raises(ValueError):
        FleetAutoscaler(min_replicas=3, max_replicas=2)
    with pytest.raises(ValueError):
        FleetAutoscaler(up_burn=0.2, down_burn=0.25)
    with pytest.raises(ValueError):
        FleetAutoscaler(up_after=0)
    with pytest.raises(ValueError):
        FleetAutoscaler(cooldown_s=-1.0)


# -- router actuation ------------------------------------------------------


class _Bad:
    status, ttft_s, itl_s_avg = "completed", 9.0, 0.9


def test_router_tick_scales_up_under_burst_then_down(compiled, flight):
    """End-to-end actuation: a seeded burn burst makes tick() spawn a
    real replica; once the burn clears and the cooldown passes, tick()
    drains one down — and it stays down (no canary restart)."""

    def factory():
        return InferenceEngine(compiled, max_slots=3, max_prompt_len=8,
                               max_len=24, queue_depth=16)

    rs = ReplicaSet(factory, initial=1)
    auto = _auto(max_replicas=2, up_after=2, down_after=3, cooldown_s=50.0)
    router = Router(rs, autoscaler=auto)
    try:
        for _ in range(6):
            rs.get("r0").engine.slo.record(_Bad())
        router.tick(now=0.0)
        acts = router.tick(now=10.0)
        assert acts["scale"] == "up"
        assert len(rs.serving()) == 2

        # Burn clears: hand every replica a fresh (empty) ledger, the
        # burn signal the quiet tail would produce.
        for rep in rs.serving():
            rep.engine.slo = GoodputLedger()
        down = None
        for i, t in enumerate((70.0, 80.0, 90.0, 100.0)):
            acts = router.tick(now=t)
            if acts["scale"] == "down":
                down = t
                break
        assert down is not None
        victims = [r for r in rs.replicas.values() if r.scale_down]
        assert len(victims) == 1
        deadline = time.monotonic() + 10
        while victims[0].state != "dead" and time.monotonic() < deadline:
            router.tick(now=down + 1.0)
            time.sleep(0.01)
        assert victims[0].state == "dead" and victims[0].drained
        assert len(rs.serving()) == 1
        directions = [e.detail["direction"]
                      for e in flight.events(kind="fleet_scale")]
        assert directions == ["up", "down"]
    finally:
        router.close()


def test_scale_down_victim_is_cheapest_replica(compiled):
    """The drain victim is the lowest-dispatch-cost (least loaded)
    serving replica — shedding the busy one would requeue more work."""

    def factory():
        return InferenceEngine(compiled, max_slots=3, max_prompt_len=8,
                               max_len=24, queue_depth=16)

    rs = ReplicaSet(factory, initial=2)
    auto = _auto(max_replicas=2, down_after=1, cooldown_s=0.0)
    router = Router(rs, autoscaler=auto)
    try:
        # Pin the saturation signal: r0 reads loaded, r1 idle.
        rs.get("r0").load_score = lambda: 0.9
        acts = router.tick(now=0.0)
        assert acts["scale"] == "down"
        assert rs.get("r1").scale_down and not rs.get("r0").scale_down
    finally:
        router.close()
