"""Test harness: 8 virtual CPU devices.

The reference tests distributed semantics on Spark ``local[N]`` threads
(SURVEY.md §4); the TPU-native translation is
``--xla_force_host_platform_device_count=8`` fake CPU devices — real
mesh/shard_map/psum semantics, no TPU required. This must run before JAX
initializes a backend, hence the env/config mutation at conftest import.
"""

import os

_FLAG = "--xla_force_host_platform_device_count=8"
_existing = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _existing:
    os.environ["XLA_FLAGS"] = (_existing + " " + _FLAG).strip()

import jax  # noqa: E402

# The dev harness pins JAX_PLATFORMS to a TPU plugin via sitecustomize;
# config.update outranks it and keeps the suite on the virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


def make_blobs(n=512, num_classes=4, dim=20, seed=0, one_hot=True, spread=3.0):
    """Linearly-separable Gaussian blobs — the synthetic stand-in for the
    reference's tiny MNIST fixtures (fast, deterministic, convergeable)."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(scale=spread, size=(num_classes, dim))
    labels = rng.integers(0, num_classes, size=n)
    features = centers[labels] + rng.normal(scale=1.0, size=(n, dim))
    features = features.astype(np.float32)
    if one_hot:
        eye = np.eye(num_classes, dtype=np.float32)
        return features, eye[labels]
    return features, labels.astype(np.int32)


@pytest.fixture()
def blobs():
    return make_blobs()


# -- runtime lock sanitizer ---------------------------------------------------

#: Concurrency suites run with the lock sanitizer ON: every
#: ``make_lock``-routed lock (buffer version guard, RWLock, telemetry
#: store, flight recorder, alert engine, request queue, fleet
#: router/replica, snapshot-encode cache) order-checks each acquisition
#: against the statically derived graph (ANALYSIS.json) plus every
#: order observed in-process, and RAISES on inversion instead of
#: deadlocking CI. Other suites keep the zero-overhead plain-lock path.
_SANITIZED_SUITES = {
    "test_hogwild_races",
    "test_rwlock",
    "test_opsd",
    "test_fleet",
    "test_fleet_serving",
    "test_locksan",
}


@pytest.fixture(autouse=True)
def _lock_sanitizer(request):
    mod = getattr(request.node, "module", None)
    name = (mod.__name__ if mod is not None else "").rsplit(".", 1)[-1]
    if name not in _SANITIZED_SUITES or name == "test_locksan":
        # test_locksan drives enable()/disable() itself
        yield
        return
    from pathlib import Path

    from elephas_tpu.utils import locksan

    analysis = Path(__file__).resolve().parent.parent / "ANALYSIS.json"
    locksan.enable(analysis_path=analysis if analysis.exists() else None)
    try:
        yield
    finally:
        locksan.disable()
