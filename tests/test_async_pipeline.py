"""Unit tests for the pipelined comms thread (engine/async_engine.py's
``_CommsPipeline``): FIFO delta ordering, prefetch consumption, the
bounded-queue backpressure, and the retry/fail-fast contract mirrored
from ``run_unit`` (transient push failures retry the SAME delta —
at-least-once, so double-apply is possible; ``ParameterServerUnavailable``
is fatal and never retried).
"""

import threading
import time

import pytest

from elephas_tpu import obs
from elephas_tpu.engine.async_engine import _CommsPipeline
from elephas_tpu.parameter.client import ParameterServerUnavailable


class FakeClient:
    """Records wire traffic; scriptable failures.

    ``push_failures`` maps a delta value to a list of exceptions raised
    on successive attempts (popped front-first). When
    ``record_before_raise`` is set, the delta is recorded BEFORE the
    exception — modelling a push that applied server-side but whose ack
    was lost, the scenario that makes retry at-least-once.
    """

    def __init__(self, record_before_raise=False):
        self.pulls = 0
        self.pushed = []
        self.push_failures = {}
        self.record_before_raise = record_before_raise
        self.pull_error = None
        self.gate = None  # threading.Event: block pushes until set

    def get_parameters(self):
        self.pulls += 1
        if self.pull_error is not None:
            raise self.pull_error
        return {"w": self.pulls}

    def update_parameters(self, delta):
        if self.gate is not None:
            assert self.gate.wait(10.0)
        planned = self.push_failures.get(delta)
        if planned:
            exc = planned.pop(0)
            if self.record_before_raise:
                self.pushed.append(delta)
            raise exc
        self.pushed.append(delta)


def _closing(pipeline):
    class _Ctx:
        def __enter__(self):
            return pipeline

        def __exit__(self, *exc):
            pipeline.close()

    return _Ctx()


def test_pushes_apply_in_fifo_order():
    client = FakeClient()
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3)) as pipe:
        for i in range(10):
            pipe.push(i)
        pipe.flush()
    assert client.pushed == list(range(10))


def test_prefetch_is_consumed_by_next_pull():
    client = FakeClient()
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3)) as pipe:
        pipe.prefetch()
        pipe.prefetch()  # no-op while one is pending
        first = pipe.pull()
        assert first == {"w": 1}
        assert client.pulls == 1  # double prefetch did not double pull
        assert pipe.pull() == {"w": 2}  # no prefetch pending → sync pull


def test_pull_orders_after_earlier_pushes():
    """A prefetch enqueued after pushes must observe them (single FIFO
    thread): the pull happens only once the deltas went out."""
    client = FakeClient()
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3)) as pipe:
        pipe.push("d0")
        pipe.push("d1")
        pipe.prefetch()
        pipe.pull()
        assert client.pushed == ["d0", "d1"]


def test_transient_push_failure_retries_same_delta():
    client = FakeClient(record_before_raise=True)
    client.push_failures["d0"] = [RuntimeError("flake"), RuntimeError("flake")]
    retries = obs.default_registry().counter(
        "ps_push_retry_total", labelnames=("worker",))
    before = retries.value
    with _closing(_CommsPipeline(client, 0, max_push_attempts=4)) as pipe:
        pipe.push("d0")
        pipe.flush()
    # Applied on every attempt: the double-push (at-least-once) contract.
    assert client.pushed == ["d0", "d0", "d0"]
    after = retries.value
    assert after - before == 2
    # The retry counter carries the worker dimension as a label now.
    assert retries.labels(worker="w0").value >= 2


def test_push_retries_exhausted_becomes_fatal():
    client = FakeClient()
    client.push_failures["d0"] = [RuntimeError("flake")] * 2
    pipe = _CommsPipeline(client, 0, max_push_attempts=2)
    try:
        pipe.push("d0")
        with pytest.raises(RuntimeError, match="flake"):
            pipe.flush()
        with pytest.raises(RuntimeError, match="flake"):
            pipe.push("d1")
    finally:
        pipe.close()
    assert client.pushed == []  # d1 never reached the wire


def test_ps_unavailable_push_is_fatal_not_retried():
    client = FakeClient()
    client.push_failures["d0"] = [
        ParameterServerUnavailable("ps dead"),
        ParameterServerUnavailable("ps dead"),
    ]
    pipe = _CommsPipeline(client, 0, max_push_attempts=5)
    try:
        pipe.push("d0")
        with pytest.raises(ParameterServerUnavailable):
            pipe.flush()
    finally:
        pipe.close()
    # Exactly ONE attempt consumed: fail-fast, no retry of infra death.
    assert len(client.push_failures["d0"]) == 1


def test_ps_unavailable_pull_surfaces_and_poisons():
    client = FakeClient()
    client.pull_error = ParameterServerUnavailable("ps dead")
    pipe = _CommsPipeline(client, 0, max_push_attempts=3)
    try:
        pipe.prefetch()
        with pytest.raises(ParameterServerUnavailable):
            pipe.pull()
        with pytest.raises(ParameterServerUnavailable):
            pipe.push("d0")  # subsequent ops re-raise the recorded fatal
    finally:
        pipe.close()


def test_transient_pull_failure_is_not_fatal():
    """Pull retry belongs to run_unit: the error surfaces once and the
    pipeline keeps working."""
    client = FakeClient()
    client.pull_error = RuntimeError("flake")
    pipe = _CommsPipeline(client, 0, max_push_attempts=3)
    try:
        with pytest.raises(RuntimeError, match="flake"):
            pipe.pull()
        client.pull_error = None
        assert pipe.pull() == {"w": 2}
        pipe.push("d0")
        pipe.flush()
        assert client.pushed == ["d0"]
    finally:
        pipe.close()


def test_bounded_queue_applies_backpressure():
    client = FakeClient()
    client.gate = threading.Event()  # wedge the wire
    pipe = _CommsPipeline(client, 0, max_push_attempts=3)
    n_target = 8
    enqueued = []

    def producer():
        for i in range(n_target):
            pipe.push(i)
            enqueued.append(i)

    t = threading.Thread(target=producer, daemon=True)
    try:
        t.start()
        deadline = time.monotonic() + 5.0
        while len(enqueued) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # let it overrun the bound if it were unbounded
        # Wire wedged: the producer must be blocked well short of
        # n_target (1 in-flight + queue maxsize + 1 in push()).
        assert len(enqueued) < n_target
        client.gate.set()
        t.join(10.0)
        assert not t.is_alive()
        pipe.flush()
        assert client.pushed == list(range(n_target))
    finally:
        client.gate.set()
        pipe.close()


def test_flush_waits_for_all_pushes_not_prefetch():
    client = FakeClient()
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3)) as pipe:
        for i in range(3):
            pipe.push(i)
        pipe.prefetch()
        pipe.flush()
        assert client.pushed == [0, 1, 2]
        assert pipe.pull() is not None  # prefetch still consumable


def test_close_is_idempotent_and_safe_after_fatal():
    client = FakeClient()
    client.push_failures["d0"] = [ParameterServerUnavailable("ps dead")]
    pipe = _CommsPipeline(client, 0, max_push_attempts=3)
    pipe.push("d0")
    pipe.close()
    pipe.close()  # second close is a no-op, not an error


# --------------------------------------------------------------------------
# Adaptive sync-interval ratchet (bounded-staleness client half)
# --------------------------------------------------------------------------


def _reject(lag=5, bound=2):
    from elephas_tpu.parameter.client import StaleDeltaRejected

    return StaleDeltaRejected("127.0.0.1:0", version=lag, lag=lag,
                              max_staleness=bound)


def test_sync_interval_validates_and_stamps_client():
    client = FakeClient()
    with pytest.raises(ValueError, match="sync_interval"):
        _CommsPipeline(client, 0, max_push_attempts=3, sync_interval=0.5)
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3,
                                 sync_interval=2.0)) as pipe:
        assert pipe.sync_interval == 2.0
        # The stamp rides every push frame to the PS ledger / SYNC column.
        assert client.sync_interval == 2.0
        gauge = obs.default_registry().gauge(
            "worker_sync_interval", labelnames=("worker",))
        assert gauge.labels(worker="w0").value == 2.0


def test_pushes_coalesce_per_interval_and_flush_sends_remainder():
    """interval=3 → one wire push per 3 units, tree-summed; flush
    flushes a partial accumulator so no delta is ever stranded."""
    client = FakeClient()
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3,
                                 sync_interval=3.0)) as pipe:
        for delta in (1, 2, 3):  # scalar leaves sum like tree leaves
            pipe.push(delta)
        pipe.flush()
        assert client.pushed == [6]
        pipe.push(4)
        pipe.push(5)
        pipe.flush()  # remainder (2 of 3 units) goes out on flush
        assert client.pushed == [6, 9]


def test_rejection_halves_interval_drops_delta_and_forces_repull():
    client = FakeClient()
    client.push_failures[4] = [_reject()]
    pipe = _CommsPipeline(client, 0, max_push_attempts=5,
                          sleep=lambda s: None, sync_interval=4.0)
    try:
        pipe.prefetch()
        assert client.pulls == 1 or pipe._pending is not None
        for _ in range(4):
            pipe.push(1)  # coalesced sum 4 → the scripted rejection
        pipe.flush()  # the reject is definitive: flush must NOT raise
        assert pipe.rejections == 1
        assert client.pushed == []  # dropped, never retried
        assert pipe.sync_interval == 2.0  # multiplicative halving
        assert client.sync_interval == 2.0
        # The pending prefetch predates the rejection: pull() discards
        # it and goes back to the wire for the fresh version line.
        pulls_before = client.pulls
        assert pipe.pull() is not None
        assert client.pulls == pulls_before + 1
    finally:
        pipe.close()


def test_interval_floor_is_one_and_accepts_relax_back_to_baseline():
    client = FakeClient()
    client.push_failures[2] = [_reject(), _reject()]  # two rounds of 2
    pipe = _CommsPipeline(client, 0, max_push_attempts=5,
                          sleep=lambda s: None, sync_interval=2.0)
    try:
        for _ in range(2):
            pipe.push(1)
        pipe.flush()
        assert pipe.sync_interval == 1.0  # 2.0 → 1.0
        pipe.push(2)  # interval 1 → immediate wire push; scripted reject
        pipe.flush()
        assert pipe.sync_interval == 1.0  # floor: never below 1
        assert pipe.rejections == 2
        # Additive recovery: +0.25 per accepted push, capped at baseline.
        for _ in range(6):
            pipe.push(0)
            pipe.flush()
        assert pipe.sync_interval == 2.0  # 1.0 + 4*0.25, then capped
        assert client.pushed  # the accepted zero-deltas reached the wire
    finally:
        pipe.close()


def test_default_interval_is_preratchet_behavior():
    """baseline 1.0 = one wire push per unit, byte-identical cadence to
    the pre-ratchet pipeline (only counters move on rejection)."""
    client = FakeClient()
    with _closing(_CommsPipeline(client, 0, max_push_attempts=3)) as pipe:
        for i in range(5):
            pipe.push(i)
        pipe.flush()
        assert client.pushed == [0, 1, 2, 3, 4]  # no coalescing
        assert pipe.sync_interval == 1.0
