"""dp x sp training tests: ring-attention LM step over a 2x4 mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from elephas_tpu.api.compile import CompiledModel
from elephas_tpu.models import get_model
from elephas_tpu.parallel.mesh import build_mesh
from elephas_tpu.parallel.seq_parallel import (
    init_lm_state,
    make_lm_train_step,
    shard_lm_batch,
)

VOCAB, SEQ, BATCH = 64, 32, 4


def _compiled(attention, num_heads=2):
    return CompiledModel(
        get_model(
            "transformer_lm",
            vocab_size=VOCAB,
            d_model=32,
            num_heads=num_heads,
            num_layers=2,
            max_seq_len=SEQ,
            attention=attention,
        ),
        optimizer={"name": "adam", "learning_rate": 1e-2},
        loss="sparse_categorical_crossentropy",
        metrics=[],
        input_shape=(SEQ,),
        input_dtype=jnp.int32,
        seed=0,
    )


def _data(seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ + 1), dtype=np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def test_seq_parallel_step_runs_and_learns(devices):
    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("ring")
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = _data()
    tokens, targets = shard_lm_batch(mesh, tokens, targets)
    losses = []
    for _ in range(10):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]  # memorizes the fixed batch
    assert int(state.step) == 10


def test_seq_parallel_ulysses_step_runs_and_learns(devices):
    """dp x sp with attention='ulysses' (all-to-all re-sharding) trains
    through the same engine step as the ring path."""
    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("ulysses", num_heads=4)  # heads % seq_size == 0
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = shard_lm_batch(mesh, *_data())
    losses = []
    for _ in range(10):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 10


def test_ulysses_matches_ring_first_loss(devices):
    """Both sequence-parallel layouts compute EXACT attention, so their
    first-step losses coincide (identical init by construction)."""
    mesh = build_mesh(num_data=2, num_seq=4)
    tokens, targets = shard_lm_batch(mesh, *_data(seed=2))
    losses = {}
    for impl in ("ring", "ulysses"):
        compiled = _compiled(impl, num_heads=4)
        step = make_lm_train_step(compiled, mesh)
        state = init_lm_state(compiled, mesh)
        _, metrics = step(state, tokens, targets)
        losses[impl] = float(metrics["loss"])
    np.testing.assert_allclose(losses["ulysses"], losses["ring"], rtol=1e-4)


def test_ring_model_outside_shard_map_fails_clearly(devices):
    import pytest

    compiled = _compiled("ring")
    with pytest.raises(ValueError, match="attention='ring' requires"):
        compiled.apply_eval(
            compiled.params, {}, jnp.zeros((1, SEQ), dtype=jnp.int32)
        )


def test_ulysses_model_outside_shard_map_names_itself(devices):
    import pytest

    compiled = _compiled("ulysses", num_heads=4)
    with pytest.raises(ValueError, match="attention='ulysses' requires"):
        compiled.apply_eval(
            compiled.params, {}, jnp.zeros((1, SEQ), dtype=jnp.int32)
        )


def test_auto_attention_picks_ring_for_undividable_heads(devices, monkeypatch):
    """attention='auto' (VERDICT r4 #9): an LM whose head count does NOT
    divide the 4-way seq axis (6 % 4 != 0) trains without the user
    choosing a layout — auto falls back to ring (exact for any head
    count). The ring path is asserted via a trace-time call counter."""
    import elephas_tpu.parallel.ring_attention as ra

    calls = {"ring": 0}
    real = ra.ring_attention

    def counting(*args, **kwargs):
        calls["ring"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ra, "ring_attention", counting)

    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = CompiledModel(
        get_model(
            "transformer_lm", vocab_size=VOCAB, d_model=24, num_heads=6,
            num_layers=1, max_seq_len=SEQ, attention="auto",
        ),
        optimizer={"name": "adam", "learning_rate": 1e-2},
        loss="sparse_categorical_crossentropy",
        metrics=[], input_shape=(SEQ,), input_dtype=jnp.int32, seed=0,
    )
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = shard_lm_batch(mesh, *_data())
    losses = []
    for _ in range(10):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert calls["ring"] > 0  # auto resolved to the ring layout


def test_auto_attention_picks_ulysses_when_heads_divide(devices, monkeypatch):
    """With heads % seq_size == 0, auto picks the ulysses layout (one
    all-to-all shuffle beats n-1 ring hops) — counted at trace time."""
    import elephas_tpu.parallel.ulysses as ul

    calls = {"ulysses": 0}
    real = ul.ulysses_attention

    def counting(*args, **kwargs):
        calls["ulysses"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ul, "ulysses_attention", counting)

    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("auto", num_heads=4)
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = shard_lm_batch(mesh, *_data())
    _, metrics = step(state, tokens, targets)
    assert np.isfinite(float(metrics["loss"]))
    assert calls["ulysses"] > 0


def test_auto_attention_outside_shard_map_is_flash(devices):
    """Outside shard_map 'auto' is NOT an error (unlike ring/ulysses):
    it resolves to the flash dispatch, so the same model object serves
    single-device eval/predict, matching dense numerics."""
    auto = _compiled("auto", num_heads=4)
    dense = _compiled("dense", num_heads=4)
    tokens, _ = _data(seed=5)
    out_auto = auto.apply_eval(auto.params, {}, jnp.asarray(tokens))
    out_dense = dense.apply_eval(dense.params, {}, jnp.asarray(tokens))
    np.testing.assert_allclose(
        np.asarray(out_auto), np.asarray(out_dense), rtol=2e-4, atol=2e-4
    )


def test_unknown_attention_rejected_at_build():
    import pytest

    from elephas_tpu.models import get_model

    with pytest.raises(ValueError, match="unknown attention"):
        get_model("transformer_lm", attention="ulyses")  # typo must fail loudly


def test_seq_parallel_matches_single_device_loss(devices):
    """First-step loss under dp x sp must equal the unsharded dense loss."""
    mesh = build_mesh(num_data=2, num_seq=4)
    ring = _compiled("ring")
    dense = _compiled("dense")
    # identical init: same seed/arch modulo attention impl
    tokens_np, targets_np = _data(seed=1)

    step = make_lm_train_step(ring, mesh)
    state = init_lm_state(ring, mesh)
    tokens, targets = shard_lm_batch(mesh, tokens_np, targets_np)
    _, metrics = step(state, tokens, targets)
    sharded_loss = float(metrics["loss"])

    logits = dense.apply_eval(dense.params, {}, jnp.asarray(tokens_np))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    dense_loss = float(
        -np.mean(
            np.take_along_axis(np.asarray(logp), targets_np[..., None], axis=-1)
        )
    )
    np.testing.assert_allclose(sharded_loss, dense_loss, rtol=1e-4)
