"""dp x sp training tests: ring-attention LM step over a 2x4 mesh."""

import jax
import jax.numpy as jnp
import numpy as np

from elephas_tpu.api.compile import CompiledModel
from elephas_tpu.models import get_model
from elephas_tpu.parallel.mesh import build_mesh
from elephas_tpu.parallel.seq_parallel import (
    init_lm_state,
    make_lm_train_step,
    shard_lm_batch,
)

VOCAB, SEQ, BATCH = 64, 32, 4


def _compiled(attention, num_heads=2):
    return CompiledModel(
        get_model(
            "transformer_lm",
            vocab_size=VOCAB,
            d_model=32,
            num_heads=num_heads,
            num_layers=2,
            max_seq_len=SEQ,
            attention=attention,
        ),
        optimizer={"name": "adam", "learning_rate": 1e-2},
        loss="sparse_categorical_crossentropy",
        metrics=[],
        input_shape=(SEQ,),
        input_dtype=jnp.int32,
        seed=0,
    )


def _data(seed=0):
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, VOCAB, size=(BATCH, SEQ + 1), dtype=np.int32)
    return tokens[:, :-1], tokens[:, 1:]


def test_seq_parallel_step_runs_and_learns(devices):
    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("ring")
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = _data()
    tokens, targets = shard_lm_batch(mesh, tokens, targets)
    losses = []
    for _ in range(10):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]  # memorizes the fixed batch
    assert int(state.step) == 10


def test_seq_parallel_ulysses_step_runs_and_learns(devices):
    """dp x sp with attention='ulysses' (all-to-all re-sharding) trains
    through the same engine step as the ring path."""
    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("ulysses", num_heads=4)  # heads % seq_size == 0
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = shard_lm_batch(mesh, *_data())
    losses = []
    for _ in range(10):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert int(state.step) == 10


def test_ulysses_matches_ring_first_loss(devices):
    """Both sequence-parallel layouts compute EXACT attention, so their
    first-step losses coincide (identical init by construction)."""
    mesh = build_mesh(num_data=2, num_seq=4)
    tokens, targets = shard_lm_batch(mesh, *_data(seed=2))
    losses = {}
    for impl in ("ring", "ulysses"):
        compiled = _compiled(impl, num_heads=4)
        step = make_lm_train_step(compiled, mesh)
        state = init_lm_state(compiled, mesh)
        _, metrics = step(state, tokens, targets)
        losses[impl] = float(metrics["loss"])
    np.testing.assert_allclose(losses["ulysses"], losses["ring"], rtol=1e-4)


def test_ring_model_outside_shard_map_fails_clearly(devices):
    import pytest

    compiled = _compiled("ring")
    with pytest.raises(ValueError, match="attention='ring' requires"):
        compiled.apply_eval(
            compiled.params, {}, jnp.zeros((1, SEQ), dtype=jnp.int32)
        )


def test_ulysses_model_outside_shard_map_names_itself(devices):
    import pytest

    compiled = _compiled("ulysses", num_heads=4)
    with pytest.raises(ValueError, match="attention='ulysses' requires"):
        compiled.apply_eval(
            compiled.params, {}, jnp.zeros((1, SEQ), dtype=jnp.int32)
        )


def test_auto_attention_picks_ring_for_undividable_heads(devices, monkeypatch):
    """attention='auto' (VERDICT r4 #9): an LM whose head count does NOT
    divide the 4-way seq axis (6 % 4 != 0) trains without the user
    choosing a layout — auto falls back to ring (exact for any head
    count). The ring path is asserted via a trace-time call counter."""
    import elephas_tpu.parallel.ring_attention as ra

    calls = {"ring": 0}
    real = ra.ring_attention

    def counting(*args, **kwargs):
        calls["ring"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ra, "ring_attention", counting)

    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = CompiledModel(
        get_model(
            "transformer_lm", vocab_size=VOCAB, d_model=24, num_heads=6,
            num_layers=1, max_seq_len=SEQ, attention="auto",
        ),
        optimizer={"name": "adam", "learning_rate": 1e-2},
        loss="sparse_categorical_crossentropy",
        metrics=[], input_shape=(SEQ,), input_dtype=jnp.int32, seed=0,
    )
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = shard_lm_batch(mesh, *_data())
    losses = []
    for _ in range(10):
        state, metrics = step(state, tokens, targets)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0]
    assert calls["ring"] > 0  # auto resolved to the ring layout


def test_auto_attention_picks_ulysses_when_heads_divide(devices, monkeypatch):
    """With heads % seq_size == 0, auto picks the ulysses layout (one
    all-to-all shuffle beats n-1 ring hops) — counted at trace time."""
    import elephas_tpu.parallel.ulysses as ul

    calls = {"ulysses": 0}
    real = ul.ulysses_attention

    def counting(*args, **kwargs):
        calls["ulysses"] += 1
        return real(*args, **kwargs)

    monkeypatch.setattr(ul, "ulysses_attention", counting)

    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("auto", num_heads=4)
    step = make_lm_train_step(compiled, mesh)
    state = init_lm_state(compiled, mesh)
    tokens, targets = shard_lm_batch(mesh, *_data())
    _, metrics = step(state, tokens, targets)
    assert np.isfinite(float(metrics["loss"]))
    assert calls["ulysses"] > 0


def test_auto_attention_outside_shard_map_is_flash(devices):
    """Outside shard_map 'auto' is NOT an error (unlike ring/ulysses):
    it resolves to the flash dispatch, so the same model object serves
    single-device eval/predict, matching dense numerics."""
    auto = _compiled("auto", num_heads=4)
    dense = _compiled("dense", num_heads=4)
    tokens, _ = _data(seed=5)
    out_auto = auto.apply_eval(auto.params, {}, jnp.asarray(tokens))
    out_dense = dense.apply_eval(dense.params, {}, jnp.asarray(tokens))
    np.testing.assert_allclose(
        np.asarray(out_auto), np.asarray(out_dense), rtol=2e-4, atol=2e-4
    )


def test_unknown_attention_rejected_at_build():
    import pytest

    from elephas_tpu.models import get_model

    with pytest.raises(ValueError, match="unknown attention"):
        get_model("transformer_lm", attention="ulyses")  # typo must fail loudly


def test_seq_parallel_trainer_fit_history_and_eval(devices):
    """The fit-shaped long-context driver: shuffled epochs, per-epoch
    history, validation, callbacks — SparkModel.fit ergonomics over the
    dp×sp step (the surface the builder-level API lacked)."""
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer

    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("auto", num_heads=4)
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, VOCAB, size=(32, SEQ + 1), dtype=np.int32)
    val = rng.integers(0, VOCAB, size=(8, SEQ + 1), dtype=np.int32)

    seen = []
    trainer = SeqParallelTrainer(compiled, mesh)
    state, history = trainer.fit(
        tokens, epochs=4, batch_size=8, validation_tokens=val,
        callbacks=[lambda e, s, m: seen.append((e, float(m["loss"])))],
    )
    assert len(history["loss"]) == 4
    assert len(history["val_loss"]) == 4
    assert history["loss"][-1] < history["loss"][0]  # memorizes the set
    assert [e for e, _ in seen] == [0, 1, 2, 3]
    assert int(state.step) == 4 * (32 // 8)
    # evaluate() agrees with the val history's last entry.
    ev = trainer.evaluate(state, val, batch_size=8)
    np.testing.assert_allclose(ev["loss"], history["val_loss"][-1], rtol=1e-5)


def test_seq_parallel_trainer_resume_and_sptp(devices):
    """Resume from a returned state (step keeps counting) — on the
    COMPOSED 2×2×2 sp×tp mesh, params staying model-sharded through
    fit/eval."""
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer

    mesh = build_mesh(num_data=2, num_seq=2, num_model=2)
    seq = 16
    compiled = CompiledModel(
        get_model("transformer_lm", vocab_size=VOCAB, d_model=16, num_heads=2,
                  num_layers=1, max_seq_len=seq, attention="ring"),
        optimizer={"name": "adam", "learning_rate": 1e-2},
        loss="sparse_categorical_crossentropy",
        metrics=[], input_shape=(seq,), input_dtype=jnp.int32, seed=0,
    )
    rng = np.random.default_rng(1)
    tokens = rng.integers(0, VOCAB, size=(16, seq + 1), dtype=np.int32)
    trainer = SeqParallelTrainer(compiled, mesh)
    state, h1 = trainer.fit(tokens, epochs=2, batch_size=8)
    assert int(state.step) == 4
    qkv = state.params["Block_0"]["SelfAttention_0"]["qkv"]["kernel"]
    assert qkv.sharding.shard_shape(qkv.shape)[2] == qkv.shape[2] // 2
    state2, h2 = trainer.fit(tokens, epochs=2, batch_size=8,
                             initial_state=state)
    assert int(state2.step) == 8
    assert h2["loss"][-1] < h1["loss"][0]


def test_seq_parallel_trainer_resume_continues_shuffle_schedule(devices):
    """A 2+2-epoch resumed fit must follow the SAME batch order as a
    straight 4-epoch fit (the shuffle stream is keyed on the global
    epoch from the restored step, not restarted at 0) — bitwise-equal
    final parameters prove it."""
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer

    mesh = build_mesh(num_data=2, num_seq=4)
    rng = np.random.default_rng(7)
    tokens = rng.integers(0, VOCAB, size=(32, SEQ + 1), dtype=np.int32)

    t1 = SeqParallelTrainer(_compiled("ring"), mesh)
    straight, _ = t1.fit(tokens, epochs=4, batch_size=8, seed=3)

    t2 = SeqParallelTrainer(_compiled("ring"), mesh)
    mid, _ = t2.fit(tokens, epochs=2, batch_size=8, seed=3)
    resumed, _ = t2.fit(tokens, epochs=2, batch_size=8, seed=3,
                        initial_state=mid)
    for a, b in zip(
        jax.tree_util.tree_leaves(jax.device_get(straight.params)),
        jax.tree_util.tree_leaves(jax.device_get(resumed.params)),
    ):
        np.testing.assert_array_equal(a, b)


def test_seq_parallel_trainer_small_and_ragged_validation(devices):
    """A validation set smaller than batch_size must not abort the fit
    (val batch clamps down), and a ragged set is evaluated EXACTLY via
    a weighted final partial batch — matching a one-batch whole-set
    evaluation to float tolerance."""
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer

    mesh = build_mesh(num_data=2, num_seq=4)
    rng = np.random.default_rng(9)
    tokens = rng.integers(0, VOCAB, size=(16, SEQ + 1), dtype=np.int32)
    small_val = rng.integers(0, VOCAB, size=(4, SEQ + 1), dtype=np.int32)

    trainer = SeqParallelTrainer(_compiled("ring"), mesh)
    state, history = trainer.fit(
        tokens, epochs=1, batch_size=8, validation_tokens=small_val
    )
    assert len(history["val_loss"]) == 1  # 4-row val under batch_size 8: fine

    ragged = rng.integers(0, VOCAB, size=(10, SEQ + 1), dtype=np.int32)
    chunked = trainer.evaluate(state, ragged, batch_size=8)  # 8 + 2 rows
    whole = trainer.evaluate(state, ragged, batch_size=10)  # one batch
    np.testing.assert_allclose(chunked["loss"], whole["loss"], rtol=1e-5)
    # batch_size below the data-axis size clamps UP (a round-down to 0
    # would loop forever) and still evaluates the whole set exactly.
    tiny_bs = trainer.evaluate(state, ragged, batch_size=1)
    np.testing.assert_allclose(tiny_bs["loss"], whole["loss"], rtol=1e-5)


def test_seq_parallel_trainer_validates_divisibility(devices):
    from elephas_tpu.parallel.seq_parallel import SeqParallelTrainer
    import pytest

    mesh = build_mesh(num_data=2, num_seq=4)
    compiled = _compiled("ring")
    trainer = SeqParallelTrainer(compiled, mesh)
    tokens = np.zeros((8, SEQ + 1), dtype=np.int32)
    with pytest.raises(ValueError, match="divide by the data-axis"):
        trainer.fit(tokens, batch_size=3)
    with pytest.raises(ValueError, match="divide"):
        trainer.fit(np.zeros((8, 31), dtype=np.int32), batch_size=2)


def test_seq_parallel_matches_single_device_loss(devices):
    """First-step loss under dp x sp must equal the unsharded dense loss."""
    mesh = build_mesh(num_data=2, num_seq=4)
    ring = _compiled("ring")
    dense = _compiled("dense")
    # identical init: same seed/arch modulo attention impl
    tokens_np, targets_np = _data(seed=1)

    step = make_lm_train_step(ring, mesh)
    state = init_lm_state(ring, mesh)
    tokens, targets = shard_lm_batch(mesh, tokens_np, targets_np)
    _, metrics = step(state, tokens, targets)
    sharded_loss = float(metrics["loss"])

    logits = dense.apply_eval(dense.params, {}, jnp.asarray(tokens_np))
    logp = jax.nn.log_softmax(logits.astype(jnp.float32))
    dense_loss = float(
        -np.mean(
            np.take_along_axis(np.asarray(logp), targets_np[..., None], axis=-1)
        )
    )
    np.testing.assert_allclose(sharded_loss, dense_loss, rtol=1e-4)
