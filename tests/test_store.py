"""Durable telemetry store (``obs/store.py``) + post-mortem incident
reconstruction (``obs/incident.py``, ``scripts/postmortem.py``).

The failure modes the store exists for are exercised directly: a torn
segment tail is walked past by readers and truncated (loudly) on warm
reopen, the disk budget prunes oldest-first per boot with the
``obs_store_bytes`` gauge tracking reality, a warm restart stitches
into one per-process story, and the whole journal replays
byte-identically under injected clocks — which is what makes the
incident digest pinnable in the chaos bench.
"""

import json
import struct
from pathlib import Path

import pytest

from elephas_tpu.obs import (
    FlightRecorder,
    IncidentBuilder,
    MetricsRegistry,
    TelemetryStore,
    Tracer,
    iter_records,
    read_store,
    store_dirs,
)
from elephas_tpu.obs import store as store_mod
from elephas_tpu.obs.fleet import FleetAggregator
from elephas_tpu.obs.opsd import ROUTES, OpsServer


def _segments(directory):
    return sorted(Path(directory).glob("seg-*.etj"))


# --------------------------------------------------------------------------
# Append path + vocabulary
# --------------------------------------------------------------------------


def test_record_vocab_and_boot_lifecycle(tmp_path):
    store = TelemetryStore(str(tmp_path), role="ps", boot="b0")
    with pytest.raises(ValueError):
        store.record("bogus", {})
    rec = store.record("flight", {"kind": "x"}, severity="warn")
    assert rec["role"] == "ps" and rec["boot"] == "b0"
    store.close()
    dump = read_store(str(tmp_path))
    # boot lifecycle, the flight record, close lifecycle — in order.
    kinds = [(r["k"], r["data"].get("event") or r["data"].get("kind"))
             for r in dump["records"]]
    assert kinds == [("lifecycle", "boot"), ("flight", "x"),
                     ("lifecycle", "close")]
    assert dump["corrupt_tails"] == []


def test_record_after_close_is_dropped_not_raised(tmp_path):
    """Teed surfaces outlive the store on kill paths — a late note must
    be swallowed, never crash the host or reopen the file."""
    store = TelemetryStore(str(tmp_path), boot="b0")
    store.close(reason="kill")
    assert store.record("flight", {"kind": "late"}) is None
    store.close()  # idempotent
    records = iter_records(str(tmp_path))[0]
    assert [r["data"].get("event") for r in records if r["k"] == "lifecycle"
            ] == ["boot", "kill"]


# --------------------------------------------------------------------------
# Corrupt tail: readers walk past, warm reopen truncates loudly
# --------------------------------------------------------------------------


def test_corrupt_tail_walked_past_and_truncated_on_reopen(tmp_path):
    store = TelemetryStore(str(tmp_path), role="ps", boot="boot-a")
    for i in range(3):
        store.record("flight", {"kind": f"ev{i}"})
    store.sync()
    # Simulate SIGKILL mid-append: a torn frame (magic + length, body
    # cut short) lands at the tail; the process never runs close().
    seg = _segments(tmp_path)[-1]
    good_size = seg.stat().st_size
    with open(seg, "ab") as f:
        f.write(b"ETJ1" + struct.pack("!I", 4096) + b"torn")

    # Readers tolerate the tail: all real records decode, the segment
    # is reported corrupt, nothing raises.
    records, corrupt = iter_records(str(tmp_path))
    assert [r["data"]["kind"] for r in records if r["k"] == "flight"] == \
        ["ev0", "ev1", "ev2"]
    assert corrupt == [str(seg)]

    # Warm reopen under a NEW boot heals the dead boot's tail: the file
    # is truncated back to the last frame boundary and the healing is
    # noted as a store_corrupt_tail flight event.
    flight = FlightRecorder(capacity=8)
    store2 = TelemetryStore(str(tmp_path), role="ps", boot="boot-b",
                            flight=flight)
    assert seg.stat().st_size == good_size
    assert store2.stats()["healed_tails"] == 1
    events = flight.snapshot()["events"]
    heal = [e for e in events if e["kind"] == "store_corrupt_tail"]
    assert len(heal) == 1 and heal[0]["severity"] == "warn"
    assert heal[0]["detail"]["path"] == seg.name
    store2.close()
    assert iter_records(str(tmp_path))[1] == []  # healed: no corrupt tails


def test_heal_never_touches_own_boot_segments(tmp_path):
    """The tail walk only truncates FOREIGN boots' segments — the open
    path must never eat bytes a concurrent self could still own."""
    store = TelemetryStore(str(tmp_path), boot="boot-a")
    store.record("flight", {"kind": "mine"})
    store.sync()
    seg = _segments(tmp_path)[-1]
    with open(seg, "ab") as f:
        f.write(b"garbage-tail")
    size = seg.stat().st_size
    again = TelemetryStore(str(tmp_path), boot="boot-a")
    assert seg.stat().st_size == size  # same boot: untouched
    assert again.stats()["healed_tails"] == 0
    again.close()


# --------------------------------------------------------------------------
# Disk budget: rotation + oldest-first pruning + gauge
# --------------------------------------------------------------------------


def test_rotation_prunes_oldest_first_and_gauge_tracks_disk(tmp_path):
    registry = MetricsRegistry()
    store = TelemetryStore(str(tmp_path), role="ps", boot="b0", keep=2,
                           segment_bytes=1024, registry=registry)
    for i in range(40):
        store.record("flight", {"kind": "spam", "detail": {"pad": "x" * 150,
                                                           "i": i}})
    stats = store.stats()
    assert stats["rotations"] > 0 and stats["pruned_segments"] > 0
    segs = _segments(tmp_path)
    assert len(segs) <= 2  # keep-N bound holds on disk
    # Oldest-first: the surviving seqs are the HIGHEST ones.
    seqs = sorted(int(p.name.split("-")[1]) for p in segs)
    assert seqs[0] == stats["segments"] - len(segs)
    # The gauge is the fleet's view of the same bytes.
    gauge = registry.gauge("obs_store_bytes", labelnames=("role",))
    assert gauge.labels(role="ps").value == float(store.disk_bytes())
    store.close()


def test_prune_spares_foreign_boot_evidence(tmp_path):
    """A restarted process on the same slot must not eat its dead
    predecessor's journal beyond its own budget: pruning is per-boot."""
    old = TelemetryStore(str(tmp_path), boot="boot-dead")
    old.record("flight", {"kind": "evidence"})
    old.sync()  # abandoned, never closed — SIGKILL
    n_old = len(_segments(tmp_path))
    new = TelemetryStore(str(tmp_path), boot="boot-live", keep=1,
                         segment_bytes=1024)
    for i in range(40):
        new.record("flight", {"kind": "spam", "detail": {"pad": "x" * 150}})
    new.close()
    survivors = {p.name for p in _segments(tmp_path)}
    assert sum("boot-dead" in n for n in survivors) == n_old
    assert sum("boot-live" in n for n in survivors) <= 1
    # And the predecessor's records still read back.
    records = iter_records(str(tmp_path))[0]
    assert any(r["k"] == "flight" and r["data"]["kind"] == "evidence"
               for r in records)


# --------------------------------------------------------------------------
# Cross-boot stitching + replay-stable rebuild
# --------------------------------------------------------------------------


def test_warm_restart_stitches_into_one_process_story(tmp_path):
    slot = tmp_path / "ps0" / "telemetry"
    first = TelemetryStore(str(slot), role="ps", boot="boot-1")
    first.record("flight", {"kind": "wal_restore"})
    first.close()
    second = TelemetryStore(str(slot), role="ps", boot="boot-2")
    second.record("flight", {"kind": "resumed"})
    second.close()

    builder = IncidentBuilder()
    assert builder.discover(str(tmp_path)) == ["ps0"]
    incident = builder.build()
    assert incident["stores"] == 1
    (proc,) = incident["processes"]
    assert proc["name"] == "ps0" and len(proc["boots"]) == 2
    assert incident["boots_by_proc"]["ps0"] == ["boot-1", "boot-2"]
    # The second boot's lifecycle record reads as a warm restart and
    # the timeline is one causally ordered story across both boots.
    names = [e["name"] for e in incident["timeline"]]
    assert names == ["boot", "wal_restore", "close",
                     "boot (warm restart)", "resumed", "close"]


def test_journal_replays_byte_identical_under_injected_clocks(
        tmp_path, monkeypatch):
    """Same injected clocks + same records ⇒ the same bytes on disk and
    the same incident digest — the property the chaos bench pins."""

    def run(directory):
        state = {"wall": 1.7e9, "mono": 50.0}

        def wall():
            state["wall"] += 0.25
            return state["wall"]

        def mono():
            state["mono"] += 0.25
            return state["mono"]

        monkeypatch.setattr(store_mod.time, "time", wall)
        store = TelemetryStore(str(directory), role="ps", boot="replay",
                               clock=mono)
        store.record("flight", {"kind": "ps_kill",
                                "detail": {"shard": 0}}, severity="error")
        store.record("alert", {"rule": "push_stall", "transition": "fire"},
                     severity="warn")
        store.record("metric", {"values": {"q": 1.0}, "tick": 0})
        store.close(reason="kill")
        monkeypatch.undo()
        return b"".join(p.read_bytes() for p in _segments(directory))

    blob_a = run(tmp_path / "a")
    blob_b = run(tmp_path / "b")
    assert blob_a == blob_b and len(blob_a) > 0

    def digest(d):
        b = IncidentBuilder()
        b.add_store(str(d), name="ps")
        return b.build()["digest"]

    assert digest(tmp_path / "a") == digest(tmp_path / "b")


def test_digest_is_order_canonical_not_timing_sensitive(tmp_path):
    """Two runs of the 'same incident' with different wall times, boot
    ids, and event ORDER produce the same digest: it hashes the sorted
    set of stable identities, never the schedule."""

    def run(directory, boot, order):
        store = TelemetryStore(str(directory), role="ps", boot=boot)
        for kind, sev in order:
            store.record("flight", {"kind": kind, "detail": {}},
                         severity=sev)
        store.close()

    run(tmp_path / "a", "boot-x", [("ps_kill", "error"),
                                   ("wal_restore", "info")])
    run(tmp_path / "b", "boot-y", [("wal_restore", "info"),
                                   ("ps_kill", "error")])

    def build(d):
        b = IncidentBuilder()
        b.add_store(str(d), name="ps")
        return b.build()

    a, b = build(tmp_path / "a"), build(tmp_path / "b")
    assert a["digest"] == b["digest"]
    # The trigger is severity-ranked, not order-ranked: both runs name
    # the error event even though run b journaled it second.
    assert a["triggering_event"]["kind"] == "ps_kill"
    assert b["triggering_event"]["kind"] == "ps_kill"


def test_cross_store_dedup_attributes_by_boot_path_then_driver(tmp_path):
    """One shared flight recorder teeing into N co-hosted stores: each
    anomaly keeps exactly one attributed copy — to the store whose boot
    the detail names, else whose slot dir the detail's path enters,
    else to the synthetic (shared)/driver slot."""
    flight = FlightRecorder(capacity=16)
    s0 = TelemetryStore(str(tmp_path / "ps0" / "telemetry"), role="ps",
                        boot="b-ps0")
    s1 = TelemetryStore(str(tmp_path / "ps1" / "telemetry"), role="ps",
                        boot="b-ps1")
    flight.attach_store(s0)
    flight.attach_store(s1)
    flight.note("ps_kill", "error", boot="b-ps1")            # boot key
    flight.note("wal_restore", "info",
                wal_dir=str(tmp_path / "ps0"))               # path key
    flight.note("worker_requeue", "warn", unit=3)            # neither
    s0.close()
    s1.close()

    builder = IncidentBuilder()
    builder.discover(str(tmp_path))
    incident = builder.build()
    assert incident["deduped_flight"] == 3  # one dropped copy per event
    by_kind = {e["name"]: e for e in incident["timeline"]
               if e["k"] == "flight"}
    assert len(by_kind) == 3
    assert by_kind["ps_kill"]["proc"] == "ps1"
    assert by_kind["wal_restore"]["proc"] == "ps0"
    assert by_kind["worker_requeue"]["proc"] == "(shared)"
    assert by_kind["worker_requeue"]["role"] == "driver"


def test_postmortem_cli_rebuilds_from_disk_only(tmp_path, capsys):
    import scripts.postmortem as pm

    slot = tmp_path / "root" / "ps0" / "telemetry"
    store = TelemetryStore(str(slot), role="ps", boot="b0")
    store.record("flight", {"kind": "ps_kill", "detail": {"shard": 0}},
                 severity="error")
    store.close(reason="kill")

    out_json = tmp_path / "incident.json"
    rc = pm.main([str(tmp_path / "root"), "--json", str(out_json)])
    assert rc == 0
    bundle = json.loads(out_json.read_text())
    assert bundle["triggering_event"]["kind"] == "ps_kill"
    assert bundle["stores"] == 1
    md = capsys.readouterr().out
    assert "ps_kill" in md and "←trigger" in md
    # An empty root is a finding, not a report.
    assert pm.main([str(tmp_path / "empty")]) == 1


# --------------------------------------------------------------------------
# Ops surface: /incidents route + fleet federation + fleet_top DISK
# --------------------------------------------------------------------------


def test_incidents_route_serves_store_doc(tmp_path):
    assert "/incidents" in ROUTES
    import urllib.request

    store = TelemetryStore(str(tmp_path), role="ps", boot="b0")
    store.record("flight", {"kind": "wal_restore"})
    server = OpsServer(port=0, registry=MetricsRegistry(),
                       tracer=Tracer(annotate_device=False),
                       flight=FlightRecorder(capacity=4),
                       incidents_fn=store.doc)
    server.start()
    try:
        with urllib.request.urlopen(f"{server.url}/incidents",
                                    timeout=5.0) as resp:
            doc = json.loads(resp.read())
        assert doc["meta"]["role"] == "ps"
        assert doc["meta"]["records"] == 2  # boot lifecycle + flight
        assert [r["k"] for r in doc["recent"]] == ["lifecycle", "flight"]
    finally:
        server.stop()
        store.close()
    # No store mounted → the route still serves, empty.
    bare = OpsServer(port=0, registry=MetricsRegistry(),
                     tracer=Tracer(annotate_device=False),
                     flight=FlightRecorder(capacity=4))
    bare.start()
    try:
        with urllib.request.urlopen(f"{bare.url}/incidents",
                                    timeout=5.0) as resp:
            assert json.loads(resp.read()) == {"meta": None, "recent": []}
    finally:
        bare.stop()


def test_fleet_federates_store_meta_and_disk_cell_renders(tmp_path):
    import scripts.fleet_top as fleet_top

    metrics = ("# TYPE obs_store_bytes gauge\n"
               'obs_store_bytes{role="ps"} 2048\n')
    incidents = {"meta": {"role": "ps", "bytes": 2048,
                          "last_record_age_s": 3.0}, "recent": []}
    bodies = {
        "/meta": json.dumps({"role": "ps", "boot": "b0"}).encode(),
        "/metrics": metrics.encode(),
        "/workers": json.dumps({"workers": {}, "total_updates": 0,
                                "unstamped_updates": 0}).encode(),
        "/alerts": json.dumps({"rules": [], "active": [], "fired": [],
                               "fired_kinds": []}).encode(),
        "/incidents": json.dumps(incidents).encode(),
    }

    def fetch(url, timeout):
        return bodies[url[len("http://ps"):]]

    agg = FleetAggregator(clock=lambda: 0.0, fetch=fetch)
    agg.add("http://ps", name="ps")
    agg.poll(now=0.0)
    snap = agg.snapshot(now=0.0)
    assert snap["incidents"]["ps"]["meta"]["bytes"] == 2048
    # The federated gauge is per-proc (proc label), never fleet-summed.
    assert any(k.startswith("obs_store_bytes{") and 'proc="ps"' in k
               for k in snap["metrics"]["gauges"])
    assert fleet_top._disk_cell(snap, "ps", "alive") == "2.0K/3s"
    # Stale/dead procs and procs with no store render '-'.
    assert fleet_top._disk_cell(snap, "ps", "stale") == "-"
    assert fleet_top._disk_cell(snap, "other", "alive") == "-"
    board = fleet_top.render(snap)
    assert "DISK" in board and "2.0K/3s" in board


def test_store_dirs_discovery_ignores_foreign_files(tmp_path):
    (tmp_path / "a" / "telemetry").mkdir(parents=True)
    (tmp_path / "a" / "telemetry" / "seg-00000000-b0.etj").write_bytes(b"")
    (tmp_path / "b").mkdir()
    (tmp_path / "b" / "notes.txt").write_text("not a segment")
    (tmp_path / "b" / "seg-junk.etj").write_bytes(b"")  # unparseable name
    assert store_dirs(str(tmp_path)) == [str(tmp_path / "a" / "telemetry")]
