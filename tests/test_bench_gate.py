"""Unit tests for ``scripts/bench_gate.py`` — the mechanical diff of a
fresh bench run against the committed artifact. ``compare``/``gate`` are
pure, so these feed literal rows; one test drives ``main`` end-to-end on
temp files to pin the exit-code contract CI gates on.
"""

import json

import pytest

import scripts.bench_gate as bg


def _checks_by_metric(checks):
    return {(c["key"], c["metric"]): c for c in checks}


def test_higher_direction_floor():
    base = [{"scenario": "s", "completed_units": 6, "wall_s": 2.0}]
    # chaos wall_s is "lower" with tol 1.00 → ceiling 4.0
    ok = bg.compare(base, [{"scenario": "s", "completed_units": 6,
                            "wall_s": 3.9}], "chaos")
    assert all(c["ok"] for c in ok)
    slow = bg.compare(base, [{"scenario": "s", "completed_units": 6,
                              "wall_s": 4.1}], "chaos")
    failed = [c for c in slow if not c["ok"]]
    assert [c["metric"] for c in failed] == ["wall_s"]
    assert "<= 4" in failed[0]["threshold"]


def test_ps_higher_metric_fails_below_floor():
    base = [{"mode": "socket", "codec": "packed", "op": "push",
             "quantize": None, "pipelined": True, "mb_per_s": 100.0}]
    fresh = [dict(base[0], mb_per_s=49.0)]  # floor is 100*(1-0.50) = 50
    checks = bg.compare(base, fresh, "ps")
    assert [c["ok"] for c in checks] == [False]
    fresh[0]["mb_per_s"] = 51.0
    assert all(c["ok"] for c in bg.compare(base, fresh, "ps"))


def test_equal_direction_is_exact():
    base = [{"scenario": "s", "completed_units": 6}]
    assert all(c["ok"] for c in bg.compare(
        base, [{"scenario": "s", "completed_units": 6}], "chaos"))
    bad = bg.compare(base, [{"scenario": "s", "completed_units": 5}],
                     "chaos")
    assert [c["ok"] for c in bad] == [False]


def test_limit_direction_ignores_baseline():
    """The serving trace-overhead guardrail is an absolute ceiling: even
    a fresh value better than baseline fails if it crosses 2%."""
    base = [{"mode": "decode", "pipeline": "on", "overhead_pct": 5.0}]
    over = bg.compare(base, [{"mode": "decode", "pipeline": "on",
                              "overhead_pct": 2.5}], "serve")
    assert [c["ok"] for c in over] == [False]
    under = bg.compare(base, [{"mode": "decode", "pipeline": "on",
                               "overhead_pct": 1.2}], "serve")
    assert [c["ok"] for c in under] == [True]


def test_missing_fresh_row_fails_row_present():
    base = [{"scenario": "kill_ps", "wall_s": 6.5}]
    checks = bg.compare(base, [{"scenario": "baseline", "wall_s": 2.0}],
                        "chaos")
    assert len(checks) == 1
    assert checks[0]["metric"] == "row_present"
    assert not checks[0]["ok"]


def test_missing_fresh_metric_fails():
    base = [{"scenario": "s", "wall_s": 2.0, "completed_units": 6}]
    fresh = [{"scenario": "s", "wall_s": 2.0}]
    by = _checks_by_metric(bg.compare(base, fresh, "chaos"))
    assert not by[("s", "completed_units")]["ok"]
    assert by[("s", "wall_s")]["ok"]


def test_meta_rows_are_skipped():
    """Rows carrying only config (the chaos ``meta`` row, serve config
    headers) produce no checks — they aren't gated metrics."""
    base = [{"scenario": "meta", "epochs": 3, "workers": 2}]
    assert bg.compare(base, [], "chaos") == []


def test_extra_fresh_rows_are_ignored():
    base = [{"scenario": "s", "completed_units": 6}]
    fresh = [{"scenario": "s", "completed_units": 6},
             {"scenario": "new_mode", "completed_units": 9}]
    assert all(c["ok"] for c in bg.compare(base, fresh, "chaos"))


def test_rows_join_on_identity_not_position():
    base = [{"mode": "a", "pipeline": "x", "tokens_per_sec": 100.0},
            {"mode": "b", "pipeline": "x", "tokens_per_sec": 10.0}]
    fresh = list(reversed([dict(r) for r in base]))
    assert all(c["ok"] for c in bg.compare(base, fresh, "serve"))


def test_gate_rolls_up_verdict():
    base = [{"scenario": "s", "completed_units": 6}]
    good = bg.gate({"chaos": (base, [dict(base[0])])})
    assert good["verdict"] == "pass"
    assert good["by_kind"]["chaos"] == {"checks": 1, "failures": 0}
    bad = bg.gate({"chaos": (base, [])})
    assert bad["verdict"] == "fail"
    assert bad["failures"][0]["metric"] == "row_present"


def test_load_rows_handles_array_and_jsonl(tmp_path):
    rows = [{"a": 1}, {"a": 2}]
    arr = tmp_path / "arr.json"
    arr.write_text(json.dumps(rows))
    jsonl = tmp_path / "rows.jsonl"
    jsonl.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    assert bg.load_rows(str(arr)) == rows
    assert bg.load_rows(str(jsonl)) == rows
    empty = tmp_path / "empty.json"
    empty.write_text("")
    assert bg.load_rows(str(empty)) == []


def test_main_exit_code_and_out_file(tmp_path, capsys):
    base = tmp_path / "base.jsonl"
    base.write_text(json.dumps({"scenario": "s", "completed_units": 6}))
    good = tmp_path / "good.jsonl"
    good.write_text(json.dumps({"scenario": "s", "completed_units": 6}))
    out = tmp_path / "verdict.json"
    verdict = bg.main(["--chaos", str(base), str(good),
                       "--out", str(out)])
    assert verdict["verdict"] == "pass"
    assert json.loads(out.read_text())["verdict"] == "pass"
    capsys.readouterr()

    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"scenario": "s", "completed_units": 5}))
    with pytest.raises(SystemExit) as exc:
        bg.main(["--chaos", str(base), str(bad)])
    assert exc.value.code == 1
    assert '"verdict": "fail"' in capsys.readouterr().out


def test_committed_artifacts_self_compare():
    """The committed baselines must pass the gate against themselves —
    pins that every artifact's shape is readable and every rule's key
    fields actually exist in the real files."""
    import pathlib

    root = pathlib.Path(bg.__file__).resolve().parent.parent
    pairs = {}
    for kind, name in (("serve", "BENCH_SERVE.json"),
                       ("ps", "BENCH_PS.json"),
                       ("chaos", "BENCH_CHAOS.json"),
                       ("fleet", "BENCH_FLEET.json")):
        path = root / name
        if path.exists():
            rows = bg.load_rows(str(path))
            pairs[kind] = (rows, rows)
    assert pairs, "no committed bench artifacts found"
    verdict = bg.gate(pairs)
    assert verdict["verdict"] == "pass", verdict["failures"]


def test_staleness_rule_gates_health_row():
    """The --health chaos row's staleness_p95 is "lower" with the
    table's loosest tolerance (2.00 → 3× ceiling): order-of-magnitude
    blowups fail, scheduling jitter does not; rows without the metric
    (every non-health scenario) are untouched by the rule."""
    base = [{"scenario": "health", "completed_units": 6,
             "staleness_p95": 4.0},
            {"scenario": "kill_worker", "completed_units": 6}]
    jitter = bg.compare(base, [
        {"scenario": "health", "completed_units": 6, "staleness_p95": 11.0},
        {"scenario": "kill_worker", "completed_units": 6}], "chaos")
    assert all(c["ok"] for c in jitter)
    blowup = bg.compare(base, [
        {"scenario": "health", "completed_units": 6, "staleness_p95": 40.0},
        {"scenario": "kill_worker", "completed_units": 6}], "chaos")
    failed = [c for c in blowup if not c["ok"]]
    assert [(c["key"], c["metric"]) for c in failed] == [
        ("health", "staleness_p95")]
    by = _checks_by_metric(bg.compare(base, base, "chaos"))
    assert ("kill_worker", "staleness_p95") not in by  # absent → not gated


def test_fleet_rules_gate_scrape_cost_and_outage_visibility():
    """The --fleet chaos row: scrape/merge costs are ABSOLUTE ceilings
    (the budget doesn't move with a loaded baseline machine), and
    fleet_saw_outage is exact — a run where the PS kill never became
    visible as dead-then-alive in the fleet view fails the gate."""
    base = [{"scenario": "fleet", "completed_units": 8,
             "fleet_scrape_ms_mean": 7.0, "fleet_merge_ms_mean": 0.2,
             "fleet_saw_outage": True}]
    good = bg.compare(base, [
        {"scenario": "fleet", "completed_units": 8,
         "fleet_scrape_ms_mean": 120.0,  # slower than base, under ceiling
         "fleet_merge_ms_mean": 40.0, "fleet_saw_outage": True}], "chaos")
    assert all(c["ok"] for c in good)

    bad = bg.compare(base, [
        {"scenario": "fleet", "completed_units": 8,
         "fleet_scrape_ms_mean": 200.0, "fleet_merge_ms_mean": 60.0,
         "fleet_saw_outage": False}], "chaos")
    failed = sorted((c["key"], c["metric"]) for c in bad if not c["ok"])
    assert failed == [("fleet", "fleet_merge_ms_mean"),
                      ("fleet", "fleet_saw_outage"),
                      ("fleet", "fleet_scrape_ms_mean")]
    # The ceilings are baseline-independent: the threshold text carries
    # the absolute limit, not a multiple of the committed number.
    by = _checks_by_metric(bad)
    assert by[("fleet", "fleet_scrape_ms_mean")]["threshold"] == \
        "must be <= 150.0"
    assert by[("fleet", "fleet_merge_ms_mean")]["threshold"] == \
        "must be <= 50.0"


def test_floor_direction_is_absolute_lower_bound():
    """ps_shard_bw_ratio: the K=4 refresh arm's effective-bandwidth
    ratio over K=1 has an absolute floor — a fresh value above the
    committed baseline still fails if it drops under 2x, because the
    claim is byte economy (K-1 not-modified shards), not a number that
    should drift with the host."""
    base = [{"mode": "shards", "codec": "packed", "op": "refresh_k4",
             "quantize": None, "pipelined": None,
             "mb_per_s": 500.0, "ps_shard_bw_ratio": 3.8}]
    good = bg.compare(base, [dict(base[0], ps_shard_bw_ratio=2.1)], "ps")
    assert all(c["ok"] for c in good)
    bad = bg.compare(base, [dict(base[0], ps_shard_bw_ratio=1.4)], "ps")
    failed = [c for c in bad if not c["ok"]]
    assert [c["metric"] for c in failed] == ["ps_shard_bw_ratio"]
    assert failed[0]["threshold"] == "must be >= 2.0"
    # Dense pull/push shard rows don't carry the ratio → untouched.
    dense = [{"mode": "shards", "codec": "packed", "op": "pull_k4",
              "quantize": None, "pipelined": None, "mb_per_s": 600.0}]
    by = _checks_by_metric(bg.compare(dense, dense, "ps"))
    assert ("shards/packed/pull_k4", "ps_shard_bw_ratio") not in by


def test_shard_kill_rules_gate_mttr_and_acked_loss():
    """The --shards chaos row: promotion MTTR is an absolute ceiling
    (detection + one client retry budget + CI headroom), and
    acked_state_recovered is exact — any acked-update loss after a
    promotion fails the gate no matter how fast it was."""
    base = [{"scenario": "shard_kill", "shard_failover_mttr_s": 2.8,
             "acked_state_recovered": True}]
    slow_but_ok = bg.compare(base, [
        {"scenario": "shard_kill", "shard_failover_mttr_s": 9.5,
         "acked_state_recovered": True}], "chaos")
    assert all(c["ok"] for c in slow_but_ok)
    bad = bg.compare(base, [
        {"scenario": "shard_kill", "shard_failover_mttr_s": 11.0,
         "acked_state_recovered": False}], "chaos")
    failed = sorted((c["key"], c["metric"]) for c in bad if not c["ok"])
    assert failed == [("shard_kill", "acked_state_recovered"),
                      ("shard_kill", "shard_failover_mttr_s")]
    by = _checks_by_metric(bad)
    assert by[("shard_kill", "shard_failover_mttr_s")]["threshold"] == \
        "must be <= 10.0"


def test_staleness_rules_gate_sweep_row():
    """The --staleness chaos row: the hard bound must have refused
    deltas (exact — the sweep is seeded and single-threaded), bounded
    arms must never converge WORSE than unbounded (absolute floor at 0
    on the recovery gain), and the swept final trees must replay
    bit-identically (digest exact). Rows without the metrics (every
    other scenario) are untouched."""
    base = [{"scenario": "staleness", "staleness_rejected_nonzero": True,
             "staleness_recovery_gain": 0.00125,
             "staleness_digest": "54f103956484907b"},
            {"scenario": "baseline", "completed_units": 8}]
    drifted = bg.compare(base, [
        {"scenario": "staleness", "staleness_rejected_nonzero": True,
         "staleness_recovery_gain": 0.0,  # below baseline, above floor
         "staleness_digest": "54f103956484907b"},
        {"scenario": "baseline", "completed_units": 8}], "chaos")
    assert all(c["ok"] for c in drifted)
    broken = bg.compare(base, [
        {"scenario": "staleness", "staleness_rejected_nonzero": False,
         "staleness_recovery_gain": -0.01,
         "staleness_digest": "deadbeefdeadbeef"},
        {"scenario": "baseline", "completed_units": 8}], "chaos")
    failed = sorted((c["key"], c["metric"]) for c in broken if not c["ok"])
    assert failed == [("staleness", "staleness_digest"),
                      ("staleness", "staleness_recovery_gain"),
                      ("staleness", "staleness_rejected_nonzero")]
    by = _checks_by_metric(bg.compare(base, base, "chaos"))
    assert by[("staleness", "staleness_recovery_gain")]["threshold"] == \
        "must be >= 0.0"
    assert ("baseline", "staleness_digest") not in by  # absent → not gated


def test_canary_overhead_rule_is_absolute_ceiling():
    """The --slo serve row's canary_overhead_pct rides the tracing
    guardrail's discipline: an absolute 2% ceiling, baseline ignored —
    a fresh run better than baseline still fails past the ceiling."""
    base = [{"mode": "serving_slo", "pipeline": True,
             "canary_overhead_pct": 4.0}]
    over = bg.compare(base, [
        {"mode": "serving_slo", "pipeline": True,
         "canary_overhead_pct": 2.5}], "serve")
    assert [c["ok"] for c in over] == [False]
    assert over[0]["threshold"] == "must be <= 2.0"
    under = bg.compare(base, [
        {"mode": "serving_slo", "pipeline": True,
         "canary_overhead_pct": 0.3}], "serve")
    assert [c["ok"] for c in under] == [True]


def test_goodput_floor_gates_slo_row_only():
    """goodput_ratio is an absolute floor on the --slo row: at bench
    scale every request should meet every objective, so dipping under
    0.9 fails regardless of baseline. Rows without the metric (every
    other serve mode) are untouched."""
    base = [{"mode": "serving_slo", "pipeline": True, "goodput_ratio": 1.0},
            {"mode": "serving", "pipeline": True, "tokens_per_sec": 50.0}]
    good = bg.compare(base, [
        {"mode": "serving_slo", "pipeline": True, "goodput_ratio": 0.95},
        {"mode": "serving", "pipeline": True, "tokens_per_sec": 50.0}],
        "serve")
    assert all(c["ok"] for c in good)
    bad = bg.compare(base, [
        {"mode": "serving_slo", "pipeline": True, "goodput_ratio": 0.85},
        {"mode": "serving", "pipeline": True, "tokens_per_sec": 50.0}],
        "serve")
    failed = [c for c in bad if not c["ok"]]
    assert [(c["key"], c["metric"]) for c in failed] == [
        ("serving_slo/True", "goodput_ratio")]
    assert failed[0]["threshold"] == "must be >= 0.9"
    by = _checks_by_metric(bg.compare(base, base, "serve"))
    assert ("serving/True", "goodput_ratio") not in by


def test_canary_outage_visibility_rule_is_exact():
    """The --shards row's canary_saw_outage is exact: a run where the
    blackbox PS probe never saw the kill (or never saw it end) fails —
    whitebox MTTR alone doesn't prove outside visibility."""
    base = [{"scenario": "shard_kill", "canary_saw_outage": True}]
    assert all(c["ok"] for c in bg.compare(
        base, [{"scenario": "shard_kill", "canary_saw_outage": True}],
        "chaos"))
    blind = bg.compare(base, [
        {"scenario": "shard_kill", "canary_saw_outage": False}], "chaos")
    assert [c["ok"] for c in blind] == [False]


def test_fleet_routed_overhead_and_token_identity_rules():
    """The fleet row's two proof bits: routed overhead is an absolute
    2% ceiling (baseline ignored), and token_identical is exact — a
    router that changes the stream fails even if it got faster."""
    base = [{"mode": "fleet_routed_vs_bare", "routed_overhead_pct": 0.3,
             "token_identical": True}]
    ok = bg.compare(base, [{
        "mode": "fleet_routed_vs_bare", "routed_overhead_pct": 1.9,
        "token_identical": True}], "fleet")
    assert all(c["ok"] for c in ok)
    bad = _checks_by_metric(bg.compare(base, [{
        "mode": "fleet_routed_vs_bare", "routed_overhead_pct": 2.4,
        "token_identical": False}], "fleet"))
    assert not bad[("fleet_routed_vs_bare", "routed_overhead_pct")]["ok"]
    assert not bad[("fleet_routed_vs_bare", "token_identical")]["ok"]


def test_fleet_affinity_floor_is_absolute():
    """affinity_hit_rate is an absolute floor (0.9): session follow-ups
    re-prefilling elsewhere is wasted work regardless of what the
    committed baseline happened to measure."""
    base = [{"mode": "fleet_n3", "affinity_hit_rate": 1.0}]
    assert all(c["ok"] for c in bg.compare(
        base, [{"mode": "fleet_n3", "affinity_hit_rate": 0.95}], "fleet"))
    low = bg.compare(
        base, [{"mode": "fleet_n3", "affinity_hit_rate": 0.5}], "fleet")
    assert [c["ok"] for c in low] == [False]


def test_fleet_kill_rules_gate_outage_and_goodput_dip():
    """The kill row's chaos gate: the fleet plane must have SEEN the
    replica die (exact), the blackbox canary outage stays under its
    ceiling, and the real-goodput dip stays above its floor."""
    base = [{"mode": "fleet_kill", "fleet_saw_replica_outage": True,
             "outage_canary_s": 0.0, "goodput_ratio_after_kill": 0.8}]
    assert all(c["ok"] for c in bg.compare(base, [{
        "mode": "fleet_kill", "fleet_saw_replica_outage": True,
        "outage_canary_s": 4.0, "goodput_ratio_after_kill": 0.6}],
        "fleet"))
    by = _checks_by_metric(bg.compare(base, [{
        "mode": "fleet_kill", "fleet_saw_replica_outage": False,
        "outage_canary_s": 30.0, "goodput_ratio_after_kill": 0.2}],
        "fleet"))
    assert not by[("fleet_kill", "fleet_saw_replica_outage")]["ok"]
    assert not by[("fleet_kill", "outage_canary_s")]["ok"]
    assert not by[("fleet_kill", "goodput_ratio_after_kill")]["ok"]


def test_fleet_autoscale_rules_are_exact():
    """Both autoscaler proof bits are equal-rules: the seeded burst
    must scale up, the post-cooldown quiet must scale down."""
    base = [{"mode": "fleet_autoscale", "scaled_up_under_burst": True,
             "scaled_down_after_cooldown": True}]
    assert all(c["ok"] for c in bg.compare(base, [dict(base[0])], "fleet"))
    stuck = _checks_by_metric(bg.compare(base, [{
        "mode": "fleet_autoscale", "scaled_up_under_burst": False,
        "scaled_down_after_cooldown": True}], "fleet"))
    assert not stuck[("fleet_autoscale", "scaled_up_under_burst")]["ok"]


def test_postmortem_rules_gate_digest_trigger_and_overhead():
    """The --postmortem chaos row: the incident digest and triggering
    event are exact (the arc is seeded and monitor-free, so the rebuilt
    timeline is replay-stable across machines), the rebuild/stability/
    trigger proof bits are exact, corrupt_tails must match the
    committed zero, and the push-path persistence tax is an absolute
    2% ceiling — baseline ignored, same discipline as the serving
    trace guardrail."""
    base = [{"scenario": "postmortem", "postmortem_rebuilt": True,
             "digest_replay_stable": True,
             "incident_digest": "9b929562d52d5a61",
             "triggering_event": "ps_kill", "trigger_is_shard_kill": True,
             "corrupt_tails": 0, "store_overhead_pct": 0.4,
             "store_overhead_within_2pct": True}]
    # Overhead drifting above baseline but under the ceiling passes.
    assert all(c["ok"] for c in bg.compare(
        base, [dict(base[0], store_overhead_pct=1.8)], "chaos"))
    broken = bg.compare(base, [dict(
        base[0], incident_digest="deadbeefdeadbeef",
        triggering_event="alert", trigger_is_shard_kill=False,
        digest_replay_stable=False, corrupt_tails=1,
        store_overhead_pct=3.1, store_overhead_within_2pct=False)],
        "chaos")
    failed = sorted(c["metric"] for c in broken if not c["ok"])
    assert failed == ["corrupt_tails", "digest_replay_stable",
                      "incident_digest", "store_overhead_pct",
                      "store_overhead_within_2pct",
                      "trigger_is_shard_kill", "triggering_event"]
    by = _checks_by_metric(broken)
    assert by[("postmortem", "store_overhead_pct")]["threshold"] == \
        "must be <= 2.0"
    # Other chaos scenarios don't carry the post-mortem metrics.
    other = [{"scenario": "baseline", "completed_units": 8}]
    by = _checks_by_metric(bg.compare(other, other, "chaos"))
    assert ("baseline", "incident_digest") not in by


def test_store_overhead_serve_rules():
    """The lm_bench --store-overhead row rides the existing 2% serving
    overhead ceiling; within_2pct pins the bench's own verdict bit and
    journaled_records must prove the store wrote during the timed
    window (floor at 1 — an empty journal measures nothing)."""
    base = [{"mode": "serving_store_overhead", "pipeline": None,
             "overhead_pct": -1.8, "within_2pct": True,
             "journaled_records": 20}]
    # Fewer records than baseline is fine (floor, not baseline diff);
    # negative overhead (store arm faster, noise) is under the ceiling.
    assert all(c["ok"] for c in bg.compare(
        base, [dict(base[0], journaled_records=3,
                    overhead_pct=1.5)], "serve"))
    by = _checks_by_metric(bg.compare(base, [dict(
        base[0], overhead_pct=2.6, within_2pct=False,
        journaled_records=0)], "serve"))
    assert not by[("serving_store_overhead", "overhead_pct")]["ok"]
    assert not by[("serving_store_overhead", "within_2pct")]["ok"]
    assert not by[("serving_store_overhead", "journaled_records")]["ok"]


def test_prefix_rules_gate_hit_rate_identity_and_itl_tail():
    """The lm_bench --prefix row: hit rate is an absolute floor (0.5),
    paged-vs-contiguous token identity is exact, and the chunked/
    unchunked ITL p99 ratio is an absolute ceiling at 1.0 — a fresh
    ratio worse than baseline but still under 1.0 passes (the claim is
    'chunking never lengthens the tail', not a baseline diff)."""
    base = [{"mode": "serving_prefix", "pipeline": True,
             "prefix_hit_rate": 0.62, "token_identical": True,
             "chunked_itl_ratio": 0.71, "all_completed": True}]
    drifted = bg.compare(base, [dict(base[0], prefix_hit_rate=0.55,
                                     chunked_itl_ratio=0.97)], "serve")
    assert all(c["ok"] for c in drifted)
    broken = bg.compare(base, [dict(base[0], prefix_hit_rate=0.4,
                                    token_identical=False,
                                    chunked_itl_ratio=1.3)], "serve")
    failed = sorted(c["metric"] for c in broken if not c["ok"])
    assert failed == ["chunked_itl_ratio", "prefix_hit_rate",
                      "token_identical"]
    by = _checks_by_metric(bg.compare(base, base, "serve"))
    key = "serving_prefix/True"
    assert (key, "prefix_hit_rate") in by
    # Rows without the prefix metrics (the plain serving arms) are
    # untouched by the new rules.
    plain = [{"mode": "serving", "pipeline": True,
              "tokens_per_sec": 100.0, "all_completed": True}]
    plain_by = _checks_by_metric(bg.compare(plain, plain, "serve"))
    assert ("serving/True", "prefix_hit_rate") not in plain_by


def test_tenant_rules_gate_conservation_overhead_and_goodput():
    """The lm_bench --tenants row: token conservation is exact (the
    committed value is 0.0 — any nonzero per-tenant/fleet diff is a
    dropped tag or a double bill), the tagged-vs-plain overhead rides
    the standing 2% absolute ceiling, the interactive tenant's goodput
    has an absolute floor even with the batch tenant saturating the
    pool, and the exemplar-to-trace join bit is exact."""
    base = [{"mode": "fleet_tenants", "tenant_token_conservation": 0.0,
             "tenant_overhead_pct": -0.4, "interactive_goodput_ratio": 1.0,
             "tenant_exemplar_joined": True, "token_identical": True,
             "all_completed": True}]
    # Overhead drifting above baseline but under the ceiling passes;
    # goodput dipping below baseline but above the floor passes.
    drifted = bg.compare(base, [dict(
        base[0], tenant_overhead_pct=1.7,
        interactive_goodput_ratio=0.4)], "fleet")
    assert all(c["ok"] for c in drifted)
    broken = bg.compare(base, [dict(
        base[0], tenant_token_conservation=3.0, tenant_overhead_pct=2.8,
        interactive_goodput_ratio=0.1, tenant_exemplar_joined=False)],
        "fleet")
    failed = sorted(c["metric"] for c in broken if not c["ok"])
    assert failed == ["interactive_goodput_ratio",
                      "tenant_exemplar_joined",
                      "tenant_overhead_pct",
                      "tenant_token_conservation"]
    by = _checks_by_metric(broken)
    assert by[("fleet_tenants", "tenant_overhead_pct")]["threshold"] == \
        "must be <= 2.0"
    assert by[("fleet_tenants", "interactive_goodput_ratio")][
        "threshold"] == "must be >= 0.25"
    # Rows without the tenancy metrics (the routed/kill/autoscale arms)
    # are untouched by the new rules.
    plain = [{"mode": "fleet_routed_vs_bare", "routed_overhead_pct": 0.3,
              "token_identical": True}]
    plain_by = _checks_by_metric(bg.compare(plain, plain, "fleet"))
    assert ("fleet_routed_vs_bare", "tenant_token_conservation") \
        not in plain_by


def test_disagg_rules_gate_identity_interference_and_handoff():
    """The lm_bench --disagg row: token identity vs the monolithic
    fleet is exact (handoff is a transport, not a resample), the
    decode-tier ITL-interference ratio is an absolute ceiling at 1.0
    (a fresh ratio worse than baseline but still under 1.0 passes —
    the claim is 'tiering never lengthens the decode tail', not a
    baseline diff), handoff p99 is an absolute ceiling, the cross-tier
    prefix hit rate has the same 0.5 floor as the single-engine
    --prefix row, and the worst tenant's goodput floor is absolute."""
    base = [{"mode": "fleet_disagg", "disagg_itl_p99_ratio": 0.45,
             "handoff_p50_ms": 3.0, "handoff_p99_ms": 12.0,
             "cross_tier_prefix_hit_rate": 0.8,
             "goodput_floor_min_tenant": 1.0,
             "token_identical": True, "all_completed": True}]
    drifted = bg.compare(base, [dict(
        base[0], disagg_itl_p99_ratio=0.9, handoff_p99_ms=200.0,
        cross_tier_prefix_hit_rate=0.55,
        goodput_floor_min_tenant=0.3)], "fleet")
    assert all(c["ok"] for c in drifted)
    broken = bg.compare(base, [dict(
        base[0], disagg_itl_p99_ratio=1.4, handoff_p99_ms=400.0,
        cross_tier_prefix_hit_rate=0.2, goodput_floor_min_tenant=0.1,
        token_identical=False)], "fleet")
    failed = sorted(c["metric"] for c in broken if not c["ok"])
    assert failed == ["cross_tier_prefix_hit_rate",
                      "disagg_itl_p99_ratio",
                      "goodput_floor_min_tenant",
                      "handoff_p99_ms", "token_identical"]
    by = _checks_by_metric(broken)
    assert by[("fleet_disagg", "disagg_itl_p99_ratio")]["threshold"] == \
        "must be <= 1.0"
    assert by[("fleet_disagg", "handoff_p99_ms")]["threshold"] == \
        "must be <= 250.0"
    assert by[("fleet_disagg", "cross_tier_prefix_hit_rate")][
        "threshold"] == "must be >= 0.5"
    assert by[("fleet_disagg", "goodput_floor_min_tenant")][
        "threshold"] == "must be >= 0.25"
    # handoff_p50 is reported but not gated (p99 is the promise), and
    # rows without the disagg metrics (the routed/kill/autoscale arms)
    # are untouched by the new rules.
    assert ("fleet_disagg", "handoff_p50_ms") not in by
    plain = [{"mode": "fleet_routed_vs_bare", "routed_overhead_pct": 0.3,
              "token_identical": True}]
    plain_by = _checks_by_metric(bg.compare(plain, plain, "fleet"))
    assert ("fleet_routed_vs_bare", "disagg_itl_p99_ratio") not in plain_by


def test_spec_rules_gate_accept_identity_and_itl_ratio():
    """The lm_bench --spec row: token identity vs the unspeculated
    oracle is exact (the speculative contract), the accept rate is an
    absolute floor at 0.5 (the bench's same-weights PS-delivered draft
    accepts ~everything — sinking under the floor means the draft
    cache/rollback mechanics broke, which never corrupts tokens, only
    acceptance), tokens_per_step is an absolute floor at 1.3 (the
    speedup claim itself), and the per-token spec/plain ITL ratio is an
    absolute ceiling at 1.0 — a fresh ratio worse than baseline but
    still under 1.0 passes (the claim is 'speculation never slows
    emission', not a baseline diff)."""
    base = [{"mode": "serving_spec", "pipeline": True, "gamma": 3,
             "spec_accept_rate": 1.0, "tokens_per_step": 3.9,
             "spec_itl_ratio": 0.32, "token_identical": True,
             "all_completed": True}]
    drifted = bg.compare(base, [dict(base[0], spec_accept_rate=0.6,
                                     tokens_per_step=1.4,
                                     spec_itl_ratio=0.95)], "serve")
    assert all(c["ok"] for c in drifted)
    broken = bg.compare(base, [dict(base[0], spec_accept_rate=0.3,
                                    tokens_per_step=1.1,
                                    spec_itl_ratio=1.2,
                                    token_identical=False)], "serve")
    failed = sorted(c["metric"] for c in broken if not c["ok"])
    assert failed == ["spec_accept_rate", "spec_itl_ratio",
                      "token_identical", "tokens_per_step"]
    by = _checks_by_metric(bg.compare(base, base, "serve"))
    key = "serving_spec/True"
    assert (key, "spec_accept_rate") in by
    assert (key, "tokens_per_step") in by
    assert (key, "spec_itl_ratio") in by
    # Rows without the spec metrics (the plain serving arms) are
    # untouched by the new rules — tokens_per_step in particular only
    # exists on the spec row, so its 1.3 floor cannot leak onto the
    # one-token-per-step baseline arms.
    plain = [{"mode": "serving", "pipeline": True,
              "tokens_per_sec": 100.0, "all_completed": True}]
    plain_by = _checks_by_metric(bg.compare(plain, plain, "serve"))
    assert ("serving/True", "spec_accept_rate") not in plain_by
    assert ("serving/True", "tokens_per_step") not in plain_by


def test_rollout_rules_drifted_pass_broken_fail():
    """The --rollout fleet row. Drift inside the envelope passes: a
    slower swap tax under the 1.5 ceiling and a lower goodput over the
    0.5 floor are CI noise, not regressions. Broken is exact: a single
    non-canary observation of the poisoned version, a non-identical
    swap stream, a leaked full transfer pushing the tax past ceiling,
    or a vanished promote/rollback arc each fail on its own rule."""
    base = [{"mode": "fleet_rollout", "token_identical": True,
             "all_completed": True, "swap_itl_p99_ratio": 1.05,
             "rollback_served_stale": 0, "rollout_goodput_ratio": 0.96,
             "rollout_promoted": 1, "rollout_rolled_back": 1}]
    drifted = bg.compare(base, [dict(base[0], swap_itl_p99_ratio=1.4,
                                     rollout_goodput_ratio=0.6)], "fleet")
    assert all(c["ok"] for c in drifted)

    broken = bg.compare(base, [dict(base[0], token_identical=False,
                                    swap_itl_p99_ratio=2.1,
                                    rollback_served_stale=3,
                                    rollout_goodput_ratio=0.2,
                                    rollout_promoted=0,
                                    rollout_rolled_back=0)], "fleet")
    failed = sorted(c["metric"] for c in broken if not c["ok"])
    assert failed == ["rollback_served_stale", "rollout_goodput_ratio",
                      "rollout_promoted", "rollout_rolled_back",
                      "swap_itl_p99_ratio", "token_identical"]
    # The containment and tax rules are absolute, not baseline-scaled.
    by = _checks_by_metric(broken)
    assert by[("fleet_rollout", "rollback_served_stale")]["threshold"] \
        == "must equal 0"
    assert by[("fleet_rollout", "swap_itl_p99_ratio")]["threshold"] == \
        "must be <= 1.5"
    # The rollout metrics exist only on the rollout row — the other
    # fleet arms (no swap tax, no rollback counters) are untouched.
    other = [{"mode": "fleet_kill", "goodput_ratio_after_kill": 0.9,
              "all_completed": True}]
    other_by = _checks_by_metric(bg.compare(other, other, "fleet"))
    assert ("fleet_kill", "swap_itl_p99_ratio") not in other_by
    assert ("fleet_kill", "rollback_served_stale") not in other_by
