"""Resilience layer units: failure detector, WAL, fault plans, ledger,
elastic pool, and the injected-sleep retry schedules.

Everything here runs on FAKE clocks/sleeps (no real waiting beyond
thread joins) — the lint (`test_lint_blocking.py`) enforces that the
production modules expose the hooks these tests drive.
"""

import threading

import numpy as np
import pytest

from elephas_tpu.checkpoint import NoCheckpointError
from elephas_tpu.parameter.client import (
    ParameterServerUnavailable,
    _RETRY_DELAYS,
    _retry_connect,
)
from elephas_tpu.resilience import (
    ALIVE,
    DEAD,
    SUSPECT,
    ElasticWorkerPool,
    FailureDetector,
    FaultInjector,
    FaultPlan,
    MembershipView,
    SnapshotWAL,
    UnitLedger,
    WalWriter,
)


class FakeClock:
    def __init__(self, now=0.0):
        self.now = now

    def __call__(self):
        return self.now

    def advance(self, dt):
        self.now += dt


class SleepRecorder:
    def __init__(self):
        self.calls = []

    def __call__(self, seconds):
        self.calls.append(seconds)


# --------------------------------------------------------------------------
# FailureDetector / MembershipView
# --------------------------------------------------------------------------


def _detector(clock, suspect_after=5.0, **kw):
    return FailureDetector(suspect_after=suspect_after, clock=clock,
                           register_metrics=False, **kw)


def test_detector_state_transitions_on_fake_clock():
    clock = FakeClock()
    det = _detector(clock)
    det.beat("w0")
    assert det.state("w0") == ALIVE
    clock.advance(5.0)  # age == suspect_after
    assert det.state("w0") == SUSPECT
    clock.advance(5.0)  # age == dead_after (2x default)
    assert det.state("w0") == DEAD
    det.beat("w0")  # revival: a beat from a dead worker rejoins
    assert det.state("w0") == ALIVE


def test_detector_sweep_is_edge_triggered():
    clock = FakeClock()
    det = _detector(clock)
    det.beat("w0")
    det.beat("w1")
    clock.advance(100.0)
    assert sorted(det.sweep()) == ["w0", "w1"]
    assert det.sweep() == []  # reported exactly once
    det.beat("w0")
    clock.advance(100.0)
    assert det.sweep() == ["w0"]  # re-dies after revival → reported again


def test_detector_deregister_is_not_an_expiry():
    clock = FakeClock()
    det = _detector(clock)
    det.beat("w0")
    det.deregister("w0")
    clock.advance(100.0)
    assert det.sweep() == []
    assert det.membership() == {}


def test_detector_membership_table_shape():
    clock = FakeClock()
    det = _detector(clock)
    det.beat("w0")
    det.beat("w0")
    clock.advance(1.5)
    table = det.membership()
    assert table["w0"]["state"] == ALIVE
    assert table["w0"]["age_s"] == pytest.approx(1.5)
    assert table["w0"]["beats"] == 2


def test_detector_expiry_counter_bumps():
    from elephas_tpu import obs

    counter = obs.default_registry().counter("ps_worker_expired_total")
    before = counter.value
    clock = FakeClock()
    det = FailureDetector(suspect_after=1.0, clock=clock)
    det.beat("w0")
    clock.advance(10.0)
    det.membership()  # reading the table IS the evaluation point
    assert counter.value == before + 1


def test_detector_validates_thresholds():
    with pytest.raises(ValueError):
        FailureDetector(suspect_after=0.0, register_metrics=False)
    with pytest.raises(ValueError):
        FailureDetector(suspect_after=5.0, dead_after=1.0,
                        register_metrics=False)


def test_membership_view_fencing_reads():
    view = MembershipView()
    assert view.state("w0") is None and not view.is_dead("w0")
    view.publish({"w0": {"state": DEAD}, "w1": {"state": ALIVE}})
    assert view.is_dead("w0") and not view.is_dead("w1")
    assert view.snapshot()["w1"]["state"] == ALIVE


# --------------------------------------------------------------------------
# SnapshotWAL / WalWriter
# --------------------------------------------------------------------------


def _tree(seed=0):
    rng = np.random.default_rng(seed)
    return {"dense": {"kernel": rng.standard_normal((4, 3)).astype(np.float32),
                      "bias": np.zeros(3, np.float32)}}


def test_wal_roundtrip_and_latest(tmp_path):
    wal = SnapshotWAL(str(tmp_path))
    with pytest.raises(NoCheckpointError):
        wal.restore_latest()  # cold start is typed
    wal.append(_tree(1), version=1)
    wal.append(_tree(2), version=2)
    assert wal.latest_version() == 2
    version, tree = wal.restore_latest()
    assert version == 2
    np.testing.assert_array_equal(tree["dense"]["kernel"],
                                  _tree(2)["dense"]["kernel"])


def test_wal_rotation_bounds_disk(tmp_path):
    wal = SnapshotWAL(str(tmp_path), keep=2)
    for v in (1, 2, 3, 4):
        wal.append(_tree(v), version=v)
    assert wal.versions() == [3, 4]


def test_wal_restore_walks_past_corrupt_tail(tmp_path):
    wal = SnapshotWAL(str(tmp_path))
    wal.append(_tree(1), version=1)
    path2 = wal.append(_tree(2), version=2)
    path2.write_bytes(path2.read_bytes()[: 40])  # torn copy of the newest
    version, tree = wal.restore_latest()
    assert version == 1
    np.testing.assert_array_equal(tree["dense"]["bias"],
                                  _tree(1)["dense"]["bias"])


def test_wal_append_is_idempotent_per_version(tmp_path):
    wal = SnapshotWAL(str(tmp_path))
    wal.append(_tree(1), version=5)
    wal.append(_tree(2), version=5)  # second writer loses, silently
    _, tree = wal.restore_latest()
    np.testing.assert_array_equal(tree["dense"]["kernel"],
                                  _tree(1)["dense"]["kernel"])


class _FakeBuffer:
    """version + get_numpy_with_version — the WalWriter's whole view."""

    def __init__(self):
        self.version = 0
        self.tree = _tree()

    def get_numpy_with_version(self):
        return self.version, self.tree


def test_wal_writer_cadence(tmp_path):
    buf = _FakeBuffer()
    writer = WalWriter(buf, SnapshotWAL(str(tmp_path)), every=2)
    buf.version = 1
    assert not writer.after_update()  # 1 version ahead < every
    buf.version = 2
    assert writer.after_update()
    assert writer.last_written == 2
    buf.version = 3
    assert not writer.after_update()
    assert writer.sync() == 3  # shutdown hook forces the tail out
    assert writer.last_written == 3


def test_wal_writer_resumes_cadence_from_durable_version(tmp_path):
    wal = SnapshotWAL(str(tmp_path))
    wal.append(_tree(), version=6)
    buf = _FakeBuffer()
    buf.version = 6
    writer = WalWriter(buf, wal, every=3)
    assert writer.last_written == 6  # warm restart: no re-snapshot at 6
    buf.version = 8
    assert not writer.after_update()
    buf.version = 9
    assert writer.after_update()


# --------------------------------------------------------------------------
# FaultPlan / FaultInjector
# --------------------------------------------------------------------------


def test_fault_plan_is_pure_in_seed_and_site():
    a = FaultPlan(seed=5, drop=0.5, delay=0.5, duplicate=0.5)
    b = FaultPlan(seed=5, drop=0.5, delay=0.5, duplicate=0.5)
    sites = [("send", "w0", s) for s in range(40)]
    assert [a.frame_action(*s) for s in sites] == \
        [b.frame_action(*s) for s in sites]
    assert a.trace_digest() == b.trace_digest()
    # consulting the same sites in a different order agrees too
    c = FaultPlan(seed=5, drop=0.5, delay=0.5, duplicate=0.5)
    for s in reversed(sites):
        c.frame_action(*s)
    assert c.trace_digest() == a.trace_digest()


def test_fault_plan_partition_window():
    plan = FaultPlan(seed=0, partition={"*": (2, 4)})
    actions = [plan.frame_action("send", "w0", s)[0] for s in range(6)]
    assert actions == ["pass", "pass", "drop", "drop", "pass", "pass"]
    labelled = FaultPlan(seed=0, partition={"w1": (0, 2)})
    assert labelled.frame_action("send", "w0", 0)[0] == "pass"
    assert labelled.frame_action("send", "w1", 0)[0] == "drop"


def test_fault_plan_worker_sites():
    plan = FaultPlan(seed=0, kill_worker_at={"w0": 2},
                     stall_worker_at={"w1": (0, 3)}, stall_seconds=7.5)
    assert not plan.should_kill("w0", 1)
    assert plan.should_kill("w0", 2)
    assert plan.stall_for("w1", 0) == 7.5
    assert plan.stall_for("w1", 1) == 0.0
    assert plan.stall_for("w1", 3) == 7.5


def test_fault_injector_drop_dup_delay_and_seq():
    sleeps = SleepRecorder()
    plan = FaultPlan(seed=0, partition={"w0": (0, 1)}, delay={"w0": 1.0},
                     duplicate={"w0": 1.0}, delay_seconds=0.25)
    injector = FaultInjector(plan, sleep=sleeps)
    sock = object()
    injector.label_socket(sock, "w0")
    with pytest.raises(ConnectionError):
        injector.on_send(sock)  # seq 0 sits in the partition window
    assert injector.on_send(sock) == "dup"  # seq 1: duplicate + delay
    assert sleeps.calls == [0.25]  # delay rode the injected sleep


def test_fault_injector_unlabeled_sockets_share_anonymous_label():
    plan = FaultPlan(seed=0, partition={"?": (0, 10)})
    injector = FaultInjector(plan)
    with pytest.raises(ConnectionError):
        injector.on_recv(object())
    # labels have independent seq streams: w0's seq 0 is its own site
    labelled = object()
    injector.label_socket(labelled, "w0")
    assert injector.on_send(labelled) == "pass"


def test_fault_injector_maybe_fail_worker():
    sleeps = SleepRecorder()
    plan = FaultPlan(seed=0, kill_worker_at={"w0": 1},
                     stall_worker_at={"w0": 0}, stall_seconds=3.0)
    injector = FaultInjector(plan, sleep=sleeps)
    injector.maybe_fail_worker("w0", 0)  # stall only
    assert sleeps.calls == [3.0]
    from elephas_tpu.resilience import InjectedWorkerDeath

    with pytest.raises(InjectedWorkerDeath):
        injector.maybe_fail_worker("w0", 1)


# --------------------------------------------------------------------------
# UnitLedger
# --------------------------------------------------------------------------


def test_ledger_leases_epoch_major():
    ledger = UnitLedger(2, [0, 1])
    order = [ledger.lease("w") for _ in range(4)]
    assert order == [(0, 0), (0, 1), (1, 0), (1, 1)]
    assert ledger.lease("w") is None


def test_ledger_requeue_goes_to_front_in_epoch_order():
    ledger = UnitLedger(2, [0, 1])
    assert ledger.lease("dead") == (0, 0)
    assert ledger.lease("dead") == (0, 1)
    assert ledger.requeue_worker("dead") == [(0, 0), (0, 1)]
    assert ledger.lease("survivor") == (0, 0)  # earliest hole first
    assert ledger.requeue_worker("dead") == []  # idempotent


def test_ledger_completion_accounting_is_exact():
    ledger = UnitLedger(1, [0, 1])
    u0, u1 = ledger.lease("w0"), ledger.lease("w1")
    counted, finished = ledger.complete("w0", u0)
    assert counted and finished is None
    counted, finished = ledger.complete("w1", u1)
    assert counted and finished == 0  # last partition closes the epoch
    assert ledger.complete("w0", u0) == (False, None)  # duplicate
    assert ledger.completed_units == 2
    assert ledger.all_done()


def test_ledger_zombie_duplicate_removes_requeued_copy():
    """A stalled worker's lease is re-queued; the zombie then finishes
    its copy. The completion counts ONCE and the pending duplicate is
    dropped so no survivor re-runs counted work."""
    ledger = UnitLedger(1, [0])
    unit = ledger.lease("zombie")
    ledger.requeue_worker("zombie")  # detector expired the stall
    counted, finished = ledger.complete("zombie", unit)  # zombie wakes
    assert counted and finished == 0
    assert ledger.lease("survivor") is None  # duplicate copy is gone
    assert ledger.all_done()
    assert ledger.completed_units == 1 == ledger.total_units


def test_ledger_rejects_empty_shapes():
    with pytest.raises(ValueError):
        UnitLedger(0, [0])
    with pytest.raises(ValueError):
        UnitLedger(1, [])


def test_ledger_batch_range_keying_and_lease_order():
    """batches_per_unit re-keys units to (epoch, partition, (lo, hi)):
    half-open ranges cover every batch exactly once, the short tail
    included, and leasing stays epoch-major."""
    ledger = UnitLedger(2, [0, 1], n_batches=5, batches_per_unit=2)
    assert ledger.ranges[0] == [(0, 2), (2, 4), (4, 5)]
    assert ledger.units_per_epoch == 6
    assert ledger.total_units == 12
    order = [ledger.lease("w") for _ in range(6)]
    assert order == [(0, 0, (0, 2)), (0, 0, (2, 4)), (0, 0, (4, 5)),
                     (0, 1, (0, 2)), (0, 1, (2, 4)), (0, 1, (4, 5))]
    assert ledger.lease("w")[0] == 1  # next epoch only after the first


def test_ledger_batch_range_per_partition_sizes():
    """n_batches may be a per-partition dict (uneven shards)."""
    ledger = UnitLedger(1, ["a", "b"], n_batches={"a": 3, "b": 1},
                        batches_per_unit=2)
    assert ledger.ranges["a"] == [(0, 2), (2, 3)]
    assert ledger.ranges["b"] == [(0, 1)]
    assert ledger.units_per_epoch == 3


def test_ledger_batches_per_unit_requires_n_batches():
    with pytest.raises(ValueError):
        UnitLedger(1, [0], batches_per_unit=2)


def test_ledger_requeue_releases_only_unfinished_ranges():
    """Requeue-on-death at batch-range granularity: the dead worker's
    FINISHED ranges stay counted; only the in-flight ones re-lease."""
    ledger = UnitLedger(1, [0], n_batches=6, batches_per_unit=2)
    first = ledger.lease("dead")
    second = ledger.lease("dead")
    assert ledger.complete("dead", first) == (True, None)
    assert ledger.requeue_worker("dead") == [second]  # not `first`
    assert ledger.lease("survivor") == second  # hole re-leases first
    assert ledger.completed_units == 1


def test_ledger_zombie_range_completion_counts_once():
    """Zombie fencing holds under range keying: the stalled worker's
    copy completing cancels the requeued duplicate."""
    ledger = UnitLedger(1, [0], n_batches=2, batches_per_unit=1)
    unit = ledger.lease("zombie")
    other = ledger.lease("zombie")
    ledger.requeue_worker("zombie")
    counted, finished = ledger.complete("zombie", unit)
    assert counted and finished is None
    # Both duplicates went back; draining them closes the epoch exactly.
    assert ledger.lease("survivor") == other
    counted, finished = ledger.complete("survivor", other)
    assert counted and finished == 0
    assert ledger.lease("survivor") is None
    assert ledger.all_done() and ledger.completed_units == 2


def test_ledger_epoch_done_fires_once_under_shuffled_completion():
    """Regression: epoch-finished accounting must compare against the
    per-epoch UNIT count, not the partition count — with ranges there
    are more units than partitions, and completions arrive out of
    order across epochs."""
    import random

    ledger = UnitLedger(2, [0, 1], n_batches=4, batches_per_unit=2)
    units = [ledger.lease("w") for _ in range(ledger.total_units)]
    random.Random(7).shuffle(units)
    fired = []
    for unit in units:
        counted, finished = ledger.complete("w", unit)
        assert counted
        if finished is not None:
            fired.append(finished)
    assert sorted(fired) == [0, 1]  # each epoch exactly once
    assert ledger.all_done()


# --------------------------------------------------------------------------
# ElasticWorkerPool (fake clients — no parameter server, no wire)
# --------------------------------------------------------------------------


class _FakeClient:
    """Liveness surface only; shared beat log stands in for the PS."""

    def __init__(self, table):
        self._table = table

    def heartbeat(self, worker_id):
        pass

    def membership(self):
        return dict(self._table)

    def health(self):
        return True

    def deregister(self, worker_id):
        pass

    def close(self):
        pass


def test_pool_drains_ledger_and_reports_stats():
    ledger = UnitLedger(3, [0, 1])
    done = []
    fired = []
    pool = ElasticWorkerPool(
        ledger,
        run_unit=lambda wid, client, unit: done.append((wid, unit)) or {"n": 1},
        client_factory=lambda wid: _FakeClient({}),
        worker_ids=["w0", "w1"],
        on_epoch_complete=fired.append,
        monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    stats = pool.wait()
    assert stats["completed_units"] == 6
    assert stats["requeued_units"] == 0
    assert fired == [0, 1, 2]  # every epoch fires exactly once, in order
    assert len(done) == 6
    assert pool.epoch_metrics()[2][1] == {"n": 1}


def test_pool_range_units_mean_into_one_metric_slot():
    """Range units report per-range metrics; the pool running-means
    them into the single (epoch, partition) slot so epoch_metrics()
    keeps its pre-range shape for downstream consumers."""
    ledger = UnitLedger(1, [0], n_batches=4, batches_per_unit=2)
    losses = iter([4.0, 2.0])

    pool = ElasticWorkerPool(
        ledger,
        run_unit=lambda wid, client, unit: {"loss": next(losses)},
        client_factory=lambda wid: _FakeClient({}),
        worker_ids=["w0"],
        monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    stats = pool.wait()
    assert stats["completed_units"] == 2
    assert pool.epoch_metrics() == {0: {0: {"loss": 3.0}}}


def test_pool_requeues_injected_death_to_survivor():
    ledger = UnitLedger(2, [0, 1])
    ran = []
    plan = FaultPlan(seed=1, kill_worker_at={"w0": 1})
    pool = ElasticWorkerPool(
        ledger,
        run_unit=lambda wid, client, unit: ran.append(wid) or {},
        client_factory=lambda wid: _FakeClient({}),
        worker_ids=["w0", "w1"],
        injector=FaultInjector(plan),
        monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    stats = pool.wait()
    assert stats["completed_units"] == 4  # exact despite the death
    deaths = stats["worker_deaths"]
    assert [d["worker"] for d in deaths] == ["w0"]
    assert deaths[0]["reason"] == "injected kill"
    assert set(ran) <= {"w0", "w1"} and ran.count("w0") == 1
    assert stats["mttr_samples"]  # the repair window was measured


def test_pool_rides_out_ps_outage_with_fresh_client():
    """First unit on w0 raises ParameterServerUnavailable; the pool
    re-queues it, polls health() on FRESH clients, and resumes. The
    wire client stays fail-fast — recovery policy lives in the pool."""
    ledger = UnitLedger(2, [0])
    state = {"failed": False, "clients": 0}

    def factory(worker_id):
        state["clients"] += 1
        return _FakeClient({})

    def run_unit(worker_id, client, unit):
        if not state["failed"]:
            state["failed"] = True
            raise ParameterServerUnavailable("boom")
        return {}

    pool = ElasticWorkerPool(
        ledger, run_unit=run_unit, client_factory=factory,
        worker_ids=["w0"], ps_recovery_grace=5.0,
        monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    stats = pool.wait()
    assert stats["completed_units"] == 2
    assert stats["requeued_units"] == 1
    outages = stats["ps_outages"]
    assert len(outages) == 1 and outages[0]["recovered"]
    # The worker's initial client plus at least one FRESH post-outage
    # client (the monitor's is lazy and may never materialize on a
    # fast drain, so it can't be counted on).
    assert state["clients"] >= 2


def test_pool_fails_fast_when_ps_never_returns():
    ledger = UnitLedger(1, [0])

    class _DeadPSClient(_FakeClient):
        def health(self):
            return False

    def run_unit(worker_id, client, unit):
        raise ParameterServerUnavailable("gone for good")

    pool = ElasticWorkerPool(
        ledger, run_unit=run_unit,
        client_factory=lambda wid: _DeadPSClient({}),
        worker_ids=["w0"], ps_recovery_grace=0.05,
        monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    with pytest.raises(ParameterServerUnavailable, match="grace"):
        pool.wait()
    assert pool.stats["ps_outages"][0]["recovered"] is False


def test_pool_admits_late_joiner():
    ledger = UnitLedger(4, [0, 1])
    gate = threading.Event()
    ran = []

    def run_unit(worker_id, client, unit):
        gate.wait(5.0)  # hold units until the joiner is in
        ran.append(worker_id)
        return {}

    pool = ElasticWorkerPool(
        ledger, run_unit=run_unit,
        client_factory=lambda wid: _FakeClient({}),
        worker_ids=["w0"], monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    pool.join_worker("late")
    with pytest.raises(ValueError):
        pool.join_worker("late")  # double-join while alive is a bug
    gate.set()
    stats = pool.wait()
    assert stats["completed_units"] == 8
    assert stats["late_joins"] == ["late"]
    assert "late" in ran


def test_pool_fences_detector_dead_worker():
    """A worker the detector declared dead must exit instead of leasing
    more work — its revival path is join_worker, not a quiet resume."""
    ledger = UnitLedger(50, [0])
    table = {"w0": {"state": "dead"}}
    started = threading.Event()

    def run_unit(worker_id, client, unit):
        started.wait(5.0)
        return {}

    pool = ElasticWorkerPool(
        ledger, run_unit=run_unit,
        client_factory=lambda wid: _FakeClient(table),
        worker_ids=["w0", "w1"], monitor_poll=0.005, idle_wait=0.001,
    )
    pool.start()
    while pool.membership.state("w0") != "dead":  # monitor publishes
        pass
    started.set()
    stats = pool.wait()
    assert stats["completed_units"] == 50  # w1 finished everything
    assert "w0" in stats["fenced"]


# --------------------------------------------------------------------------
# Injected-sleep retry schedules (satellite: no real waits in tier-1)
# --------------------------------------------------------------------------


def test_retry_connect_backoff_schedule_then_typed_error():
    sleeps = SleepRecorder()
    calls = {"n": 0}

    def always_refused():
        calls["n"] += 1
        raise ConnectionRefusedError("nope")

    with pytest.raises(ParameterServerUnavailable, match="during pull"):
        _retry_connect(always_refused, "host:1", "pull", sleep=sleeps)
    assert tuple(sleeps.calls) == _RETRY_DELAYS  # the exact schedule
    assert calls["n"] == len(_RETRY_DELAYS) + 1  # initial try + retries


def test_retry_connect_stops_sleeping_on_success():
    sleeps = SleepRecorder()
    attempts = {"n": 0}

    def flaky():
        attempts["n"] += 1
        if attempts["n"] < 3:
            raise ConnectionResetError("hiccup")
        return "ok"

    assert _retry_connect(flaky, "host:1", "push", sleep=sleeps) == "ok"
    assert tuple(sleeps.calls) == _RETRY_DELAYS[:2]  # no schedule overrun


def test_comms_pipeline_push_retry_backoff_and_counter():
    from elephas_tpu import obs
    from elephas_tpu.engine.async_engine import _CommsPipeline

    counter = obs.default_registry().counter(
        "ps_push_retry_total", labelnames=("worker",))
    before = counter.value
    sleeps = SleepRecorder()
    pushes = {"n": 0}

    class _FlakyPushClient:
        def update_parameters(self, delta):
            pushes["n"] += 1
            if pushes["n"] <= 2:
                raise RuntimeError("transient 500")

        def get_parameters(self):
            return {}

    comms = _CommsPipeline(_FlakyPushClient(), 0, max_push_attempts=4,
                           sleep=sleeps)
    try:
        comms.push({"params": {}})
        comms.flush()
    finally:
        comms.close()
    assert pushes["n"] == 3  # two transient failures, then success
    assert sleeps.calls == [0.05, 0.1]  # _PUSH_RETRY_DELAYS prefix
    assert counter.value == before + 2


def test_comms_pipeline_push_never_retries_unavailable():
    """ParameterServerUnavailable is infrastructure death: the pipeline
    records it as fatal without burning the retry schedule (a re-sent
    delta could double-apply on a healthy-again server)."""
    from elephas_tpu.engine.async_engine import _CommsPipeline

    sleeps = SleepRecorder()
    pushes = {"n": 0}

    class _DeadClient:
        def update_parameters(self, delta):
            pushes["n"] += 1
            raise ParameterServerUnavailable("gone")

    comms = _CommsPipeline(_DeadClient(), 0, max_push_attempts=4,
                           sleep=sleeps)
    try:
        comms.push({"params": {}})
        with pytest.raises(ParameterServerUnavailable):
            comms.flush()
    finally:
        comms.close()
    assert pushes["n"] == 1 and sleeps.calls == []


def test_barrier_timeout_env_hardening(monkeypatch):
    """A malformed ELEPHAS_BARRIER_TIMEOUT warns and takes the 600s
    default instead of crashing fit teardown (satellite: env parsing
    hardening). The barrier satisfies immediately, so no real waiting."""
    from elephas_tpu.parameter.client import _WireBarrierMixin

    class _InstantBarrier(_WireBarrierMixin):
        def barrier_arrive(self, tag):
            return 1

        def barrier_count(self, tag):
            return 1

    monkeypatch.setenv("ELEPHAS_BARRIER_TIMEOUT", "ten-minutes")
    with pytest.warns(RuntimeWarning, match="ELEPHAS_BARRIER_TIMEOUT"):
        _InstantBarrier().wait_barrier("teardown", 1, timeout=None)
