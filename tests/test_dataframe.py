"""DataFrame + ml/mllib adapter tests (reference adapter tests §4)."""

import numpy as np
import pytest

from elephas_tpu.data.dataframe import (
    DataFrame,
    df_to_simple_rdd,
    from_data_frame,
    to_data_frame,
)
from elephas_tpu.data import mllib


def test_dataframe_basics():
    df = DataFrame({"a": np.arange(5), "b": np.ones((5, 3))})
    assert df.count() == 5
    assert set(df.columns) == {"a", "b"}
    sel = df.select("a")
    assert sel.columns == ["a"]
    df2 = df.with_column("c", np.zeros(5))
    assert "c" in df2.columns and "c" not in df.columns
    assert df2.drop("c").columns == df.columns
    assert len(df.limit(2)) == 2
    with pytest.raises(ValueError):
        DataFrame({"a": np.arange(5), "b": np.arange(4)})
    with pytest.raises(KeyError):
        df.select("missing")


def test_dataframe_pandas_roundtrip():
    df = DataFrame({"features": np.random.default_rng(0).normal(size=(6, 4)), "label": np.arange(6.0)})
    pdf = df.to_pandas()
    back = DataFrame.from_pandas(pdf)
    np.testing.assert_allclose(back["features"], df["features"])
    np.testing.assert_allclose(back["label"], df["label"])


def test_to_from_data_frame_categorical():
    x = np.random.default_rng(0).normal(size=(12, 5)).astype(np.float32)
    y_int = np.random.default_rng(1).integers(0, 3, size=12)
    y = np.eye(3, dtype=np.float32)[y_int]
    df = to_data_frame(None, x, y, categorical=True)
    np.testing.assert_array_equal(df["label"], y_int.astype(np.float32))
    fx, fy = from_data_frame(df, categorical=True, nb_classes=3)
    np.testing.assert_allclose(fx, x)
    np.testing.assert_array_equal(fy, y)


def test_df_to_simple_rdd():
    x = np.random.default_rng(0).normal(size=(16, 5)).astype(np.float32)
    y = np.random.default_rng(1).integers(0, 2, size=16).astype(np.float32)
    df = to_data_frame(None, x, y, categorical=False)
    rdd = df_to_simple_rdd(df, categorical=True, nb_classes=2, num_partitions=4)
    assert rdd.getNumPartitions() == 4
    assert rdd.labels.shape == (16, 2)


def test_mllib_vector_roundtrip():
    v = np.array([1.0, 2.0, 3.0])
    vec = mllib.to_vector(v)
    np.testing.assert_array_equal(mllib.from_vector(vec), v)
    with pytest.raises(ValueError):
        mllib.to_vector(np.ones((2, 2)))


def test_mllib_matrix_roundtrip():
    m = np.arange(6.0).reshape(2, 3)
    mat = mllib.to_matrix(m)
    assert mat.numRows == 2 and mat.numCols == 3
    np.testing.assert_array_equal(mllib.from_matrix(mat), m)
    with pytest.raises(ValueError):
        mllib.to_matrix(np.ones(3))


def test_out_of_range_labels_raise():
    df = DataFrame({"features": np.zeros((3, 2), np.float32),
                    "label": np.array([0.0, 1.0, 5.0])})
    with pytest.raises(ValueError, match="labels outside"):
        from_data_frame(df, categorical=True, nb_classes=3)
