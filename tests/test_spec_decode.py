"""Speculative decoding (``elephas_tpu.serving.spec``).

The contract under test: with ``speculative=True`` the engine serves
every request through ONE draft program + ONE verify program, emits
between 1 and gamma + 1 tokens per lane-step — and the emitted streams
are BYTE-IDENTICAL to plain decode, greedy and temperature-matched
alike, across EOS stops, deadline evictions mid-speculation, draft-pull
failures (fallback to plain), and paged-pool churn.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from elephas_tpu import obs
from elephas_tpu.api.compile import CompiledModel
from elephas_tpu.models import get_model
from elephas_tpu.serving import (
    DraftModelSource,
    InferenceEngine,
    SelfDraftSource,
)

VOCAB, SEQ = 97, 64

PROMPTS = [
    ([5, 3, 9], 10),
    ([7, 2, 8, 4, 1, 6], 12),
    ([11, 12], 8),
    ([1, 2, 3, 4], 10),
    ([42, 7, 7, 13, 2], 9),
    ([3], 11),
]


@pytest.fixture(scope="module")
def compiled():
    return CompiledModel(
        get_model(
            "transformer_lm", vocab_size=VOCAB, d_model=32, num_heads=4,
            num_layers=2, max_seq_len=SEQ,
        ),
        optimizer={"name": "adam", "learning_rate": 3e-3},
        loss="sparse_categorical_crossentropy",
        metrics=[],
        input_shape=(SEQ,),
        input_dtype=jnp.int32,
        seed=0,
    )


def _engine(compiled, **kw):
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_prompt_len", 8)
    kw.setdefault("max_len", 24)
    kw.setdefault("queue_depth", 8)
    return InferenceEngine(compiled, **kw)


def _serve(engine, prompts=PROMPTS, **submit_kw):
    rids = [engine.submit(p, max_new_tokens=n, **submit_kw)
            for p, n in prompts]
    return [engine.result(r, timeout_s=120) for r in rids]


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class FakePSClient:
    """Stands in for ``ShardedParameterClient``: hands out a param tree
    and counts pulls (the wire client's version gating — NotModified on
    unchanged ``X-Elephas-Version`` — sits below this interface)."""

    def __init__(self, params):
        self.params = params
        self.pulls = 0
        self.fail_next = 0

    def get_parameters(self):
        if self.fail_next > 0:
            self.fail_next -= 1
            raise ConnectionError("draft pull failed (injected)")
        self.pulls += 1
        return self.params


# -- token identity --------------------------------------------------------


@pytest.mark.parametrize("pipeline", [True, False])
def test_greedy_identity_self_draft(compiled, pipeline):
    plain = [r.tokens for r in _serve(_engine(compiled, pipeline=pipeline))]
    eng = _engine(compiled, pipeline=pipeline, speculative=True, gamma=3,
                  draft_layers=1)
    spec = [r.tokens for r in _serve(eng)]
    assert spec == plain


def test_temperature_identity_self_draft(compiled):
    """Sampled decode stays byte-identical: position-keyed sampling
    draws the same random number for the same stream position no matter
    which program samples it."""
    kw = dict(temperature=0.7, top_k=5, seed=3)
    plain = [r.tokens for r in _serve(_engine(compiled, **kw))]
    spec = [r.tokens for r in _serve(_engine(
        compiled, speculative=True, gamma=3, draft_layers=1, **kw))]
    assert spec == plain


def test_greedy_identity_chunked_prefill(compiled):
    """Speculation composes with chunked prefill — both share the
    position-keyed sampler, so splitting prompts into chunks changes
    nothing."""
    plain = [r.tokens for r in _serve(_engine(compiled))]
    spec = [r.tokens for r in _serve(_engine(
        compiled, speculative=True, gamma=2, draft_layers=1,
        prefill_chunk=3, prefill_chunks_per_step=1))]
    assert spec == plain


def test_gamma_sweep_identity(compiled):
    plain = [r.tokens for r in _serve(_engine(compiled))]
    for gamma in (1, 2, 5):
        spec = [r.tokens for r in _serve(_engine(
            compiled, speculative=True, gamma=gamma, draft_layers=1))]
        assert spec == plain, f"gamma={gamma} diverged"


# -- EOS / budget ----------------------------------------------------------


def test_eos_freeze_mid_window(compiled):
    """A stop token landing anywhere inside a speculative window ends
    the stream exactly where plain decode would — later window tokens
    are discarded, never emitted."""
    plain = _serve(_engine(compiled))
    # Pick a token that actually occurs mid-stream so the stop triggers.
    stop = plain[1].tokens[4]
    kw = dict(stop_token=stop)
    base = [r.tokens for r in _serve(_engine(compiled, **kw))]
    spec = [r.tokens for r in _serve(_engine(
        compiled, speculative=True, gamma=4, draft_layers=1, **kw))]
    assert spec == base
    for toks in spec:
        assert stop not in toks[:-1]  # frozen at first occurrence


# -- accept-all / reject-all edge cases ------------------------------------


def test_accept_all_same_model_draft(compiled):
    """The target itself as draft model: every draft token matches, so
    every window emits gamma + 1 tokens and the accept rate is exactly
    1.0 — and the output is still byte-identical."""
    plain = [r.tokens for r in _serve(_engine(compiled))]
    client = FakePSClient(compiled.params)
    eng = _engine(
        compiled, speculative=True, gamma=3, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, client),
    )
    results = _serve(eng)
    assert [r.tokens for r in results] == plain
    st = eng.stats()
    assert st["spec_accept_rate"] == 1.0
    assert st["spec_tokens_per_step"] > 1.3
    assert any(r.tokens_per_step and r.tokens_per_step > 1.3
               for r in results)


def test_reject_all_zero_params_draft(compiled):
    """A draft that constantly proposes token 0 (zeroed params → flat
    logits → argmax 0): acceptance collapses to ~0, throughput
    degrades to plain decode — and output stays byte-identical."""
    plain = [r.tokens for r in _serve(_engine(compiled))]
    zeroed = jax.tree_util.tree_map(jnp.zeros_like, compiled.params)
    client = FakePSClient(zeroed)
    eng = _engine(
        compiled, speculative=True, gamma=3, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, client),
    )
    assert [r.tokens for r in _serve(eng)] == plain
    st = eng.stats()
    # Token 0 may coincide with a real target token occasionally; the
    # rate must sit at (or negligibly above) the reject-all floor.
    assert st["spec_accept_rate"] <= 0.1
    assert st["spec_tokens_per_step"] >= 1.0


# -- compile-program story -------------------------------------------------


def test_compile_counters_pinned(compiled):
    """Mixed traffic (ragged prompts, admissions mid-decode, EOS)
    compiles exactly one draft and one verify program — and the plain
    decode program never runs."""
    eng = _engine(compiled, speculative=True, gamma=3, draft_layers=1)
    _serve(eng)
    _serve(eng)  # second wave: warm programs, zero new traces
    st = eng.stats()
    assert st["draft_traces"] == 1
    assert st["verify_traces"] == 1
    assert st["prefill_traces"] == 1
    assert st["decode_traces"] == 0
    assert st["spec_fallbacks"] == 0
    assert st["spec_windows"] > 0


def test_compile_counters_model_source(compiled):
    client = FakePSClient(compiled.params)
    eng = _engine(
        compiled, speculative=True, gamma=2, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, client),
    )
    _serve(eng)
    _serve(eng)
    st = eng.stats()
    assert st["draft_traces"] == 1
    assert st["verify_traces"] == 1
    assert st["draft_prefill_traces"] == 1


# -- paged rollback / refcount conservation --------------------------------


def test_refcount_conservation_under_churn(compiled):
    """Seeded churn (ragged prompts, shared prefixes, EOS, slot reuse)
    over a speculative engine: every harvest rolls rejected suffixes
    back device-side, and the block ledger must still conserve —
    every block free or accounted for by exactly its refcount."""
    rng = np.random.default_rng(7)
    eng = _engine(compiled, speculative=True, gamma=3, draft_layers=1,
                  queue_depth=32)
    prompts = []
    for _ in range(16):
        plen = int(rng.integers(1, 8))
        if prompts and rng.random() < 0.4:
            base = prompts[int(rng.integers(0, len(prompts)))][0]
            p = (base + [int(t) for t in
                         rng.integers(1, VOCAB, plen)])[:7]
        else:
            p = [int(t) for t in rng.integers(1, VOCAB, plen)]
        prompts.append((p, int(rng.integers(2, 14))))
    results = _serve(eng, prompts=prompts)
    assert all(r.status == "completed" for r in results)
    eng.pool.assert_block_invariants()
    assert eng.pool.active_count == 0


def test_deadline_eviction_mid_speculation(compiled):
    """A deadline expiring while a speculative window is in flight
    evicts the lane cleanly: partial tokens returned, its blocks
    released (ledger conserves), survivors decode on unperturbed."""
    clock = FakeClock()
    eng = _engine(compiled, speculative=True, gamma=3, draft_layers=1,
                  clock=clock)
    doomed = eng.submit([7, 2, 8, 4, 1, 6], max_new_tokens=12,
                        timeout_s=5.0)
    survivor = eng.submit([5, 3, 9], max_new_tokens=10)
    for _ in range(3):  # a couple of windows land before the deadline
        eng.step()
        clock.advance(1.0)
    clock.advance(10.0)  # now past the doomed request's deadline
    res_d = eng.result(doomed, timeout_s=120)
    res_s = eng.result(survivor, timeout_s=120)
    assert res_d.status == "timeout"
    assert res_s.status == "completed"
    # The survivor's stream is the same one a quiet engine produces.
    quiet = _engine(compiled, speculative=True, gamma=3, draft_layers=1)
    rid = quiet.submit([5, 3, 9], max_new_tokens=10)
    assert res_s.tokens == quiet.result(rid, timeout_s=120).tokens
    eng.pool.assert_block_invariants()
    # The evicted lane's partial tokens are a prefix of its full stream.
    full = _engine(compiled, speculative=True, gamma=3, draft_layers=1)
    rid = full.submit([7, 2, 8, 4, 1, 6], max_new_tokens=12)
    assert res_d.tokens == full.result(rid, timeout_s=120).tokens[
        :len(res_d.tokens)]


# -- draft-weights delivery / fallback -------------------------------------


def test_version_gated_draft_refresh(compiled):
    """``refresh_every`` bounds pulls: a large window pulls once for the
    whole run; refresh_every=1 re-asks the (version-gating) client at
    every draft call."""
    lazy = FakePSClient(compiled.params)
    eng = _engine(
        compiled, speculative=True, gamma=2, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, lazy,
                                      refresh_every=10_000),
    )
    _serve(eng)
    assert lazy.pulls == 1

    eager = FakePSClient(compiled.params)
    eng2 = _engine(
        compiled, speculative=True, gamma=2, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, eager,
                                      refresh_every=1),
    )
    _serve(eng2)
    assert eager.pulls > 1
    assert eng.stats()["spec_accept_rate"] == 1.0


def test_spec_fallback_on_pull_failure(compiled):
    """Draft pulls failing mid-serve degrade those windows to plain
    decode (spec_fallback flight kind) — never an error, and the
    emitted streams stay byte-identical."""
    plain = [r.tokens for r in _serve(_engine(compiled))]
    client = FakePSClient(compiled.params)
    eng = _engine(
        compiled, speculative=True, gamma=2, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, client,
                                      refresh_every=1),
    )
    client.fail_next = 3  # the first pulls fail (incl. draft prefill)
    assert [r.tokens for r in _serve(eng)] == plain
    st = eng.stats()
    assert st["spec_fallbacks"] >= 1
    assert st["decode_traces"] <= 1  # at most ONE plain program compiled
    kinds = [e.kind for e in
             obs.default_flight_recorder().events(kind="spec_fallback")]
    assert "spec_fallback" in kinds


# -- metrics / plumbing ----------------------------------------------------


def test_tokens_per_step_plain_is_one(compiled):
    results = _serve(_engine(compiled))
    for r in results:
        if len(r.tokens) > 1:
            assert r.tokens_per_step == pytest.approx(1.0)


def test_spec_load_signals(compiled):
    client = FakePSClient(compiled.params)
    eng = _engine(
        compiled, speculative=True, gamma=3, prefix_cache=False,
        draft_source=DraftModelSource(compiled.module, client),
    )
    _serve(eng)
    signals = eng.load.snapshot()["signals"]
    assert signals["spec_accept_rate"] == 1.0
    assert signals["spec_tokens_per_step"] > 1.3
    plain_eng = _engine(compiled)
    _serve(plain_eng)
    assert "spec_accept_rate" not in plain_eng.load.snapshot()["signals"]


def test_spec_requires_paged_and_validates(compiled):
    with pytest.raises(ValueError, match="paged"):
        _engine(compiled, paged=False, speculative=True)
    with pytest.raises(ValueError, match="mutually exclusive"):
        _engine(compiled, speculative=True, draft_layers=1,
                draft_source=SelfDraftSource(1))
    with pytest.raises(ValueError, match="speculative"):
        _engine(compiled, draft_layers=1)
    with pytest.raises(ValueError, match="draft_layers"):
        _engine(compiled, speculative=True, draft_layers=2)  # == num_layers
    with pytest.raises(ValueError, match="prefix_cache"):
        _engine(compiled, speculative=True,
                draft_source=DraftModelSource(
                    compiled.module, FakePSClient(compiled.params)))
    with pytest.raises(ValueError, match="gamma"):
        _engine(compiled, speculative=True, gamma=0, draft_layers=1)
