"""Pipeline estimator/transformer tests (reference test_ml_model.py §4:
full fit/transform + save/load round-trips)."""

import os

import numpy as np
import pytest

from elephas_tpu.data.dataframe import to_data_frame
from elephas_tpu.ml import (
    ElephasEstimator,
    ElephasTransformer,
    load_ml_estimator,
    load_ml_transformer,
)

from conftest import make_blobs

NUM_CLASSES, DIM = 3, 12


@pytest.fixture(scope="module")
def df():
    x, y = make_blobs(n=360, num_classes=NUM_CLASSES, dim=DIM, seed=5)
    return to_data_frame(None, x, y, categorical=True)


def make_estimator(**overrides):
    est = ElephasEstimator(
        keras_model_config={
            "name": "mlp",
            "kwargs": {"features": (24,), "num_classes": NUM_CLASSES},
            "input_shape": (DIM,),
        },
        mode="synchronous",
        frequency="batch",
        nb_classes=NUM_CLASSES,
        num_workers=2,
        epochs=3,
        batch_size=16,
        optimizer_config={"name": "adam", "learning_rate": 0.01},
        loss="categorical_crossentropy",
        metrics=("acc",),
        categorical=True,
    )
    est.set_params(**overrides)
    return est


def test_fit_transform_pipeline(df):
    transformer = make_estimator().fit(df)
    assert isinstance(transformer, ElephasTransformer)
    out = transformer.transform(df)
    assert "prediction" in out.columns
    acc = float(np.mean(out["prediction"] == df["label"]))
    assert acc > 0.8
    assert transformer.history["acc"][-1] > 0.8


def test_chainable_setters(df):
    est = make_estimator()
    est.set_epochs(2).set_batch_size(8).set_output_col("guess").set_verbose(0)
    assert est.get_epochs() == 2
    transformer = est.fit(df)
    out = transformer.transform(df)
    assert "guess" in out.columns


def test_estimator_save_load_roundtrip(df, tmp_path):
    est = make_estimator()
    path = os.path.join(tmp_path, "estimator.pkl")
    est.save(path)
    loaded = load_ml_estimator(path)
    assert loaded.param_map() == est.param_map()
    transformer = loaded.fit(df)
    assert transformer.transform(df)["prediction"].shape == (len(df),)


def test_transformer_save_load_roundtrip(df, tmp_path):
    transformer = make_estimator().fit(df)
    before = transformer.transform(df)["prediction"]
    path = os.path.join(tmp_path, "transformer.pkl")
    transformer.save(path)
    loaded = load_ml_transformer(path)
    after = loaded.transform(df)["prediction"]
    np.testing.assert_array_equal(before, after)


def test_get_model_returns_trained_network(df):
    transformer = make_estimator().fit(df)
    net = transformer.get_model()
    assert net.count_params() > 0


def test_async_estimator(df):
    transformer = make_estimator(mode="asynchronous", frequency="epoch").fit(df)
    out = transformer.transform(df)
    acc = float(np.mean(out["prediction"] == df["label"]))
    assert acc > 0.8


def test_param_validation():
    with pytest.raises(ValueError):
        ElephasEstimator(bogus_param=1)
    est = ElephasEstimator()
    with pytest.raises(ValueError):  # no model config
        est.fit(to_data_frame(None, np.zeros((8, 2), np.float32), np.zeros(8), False))
    assert "mode" in est.explain_params()


def test_optimizer_config_default_not_shared():
    """Mutable Param defaults must not alias across stages."""
    a, b = ElephasEstimator(), ElephasEstimator()
    a.optimizer_config["learning_rate"] = 123.0
    assert "learning_rate" not in b.optimizer_config
    from elephas_tpu.ml.params import HasOptimizerConfig

    assert "learning_rate" not in HasOptimizerConfig._params()["optimizer_config"].default


def test_regression_transform_single_row(df):
    """categorical=False with a 1-row frame must keep the row dimension."""
    transformer = make_estimator().fit(df)
    transformer.set_categorical(False)
    one = df.limit(1)
    out = transformer.transform(one)
    assert out[transformer.output_col].shape[0] == 1


def test_estimator_autotune_param(df):
    """The stage exposes the reference-style autotune param and plumbs
    it into SparkModel (no-op A/B on the CPU backend, but the recorded
    choice proves the wiring)."""
    est = make_estimator().set_autotune(True)
    assert est.autotune is True
    assert "autotune" in est.param_map()
    transformer = est.fit(df)
    out = transformer.transform(df)
    assert out[transformer.output_col].shape[0] == len(df)


def test_wrong_kind_load_raises(tmp_path):
    est = make_estimator()
    path = os.path.join(tmp_path, "est.pkl")
    est.save(path)
    with pytest.raises(ValueError):
        load_ml_transformer(path)
