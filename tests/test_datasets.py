"""Dataset loader tests: shapes, determinism, cache-path resolution."""

import numpy as np

from elephas_tpu.data import datasets


def test_synthetic_mnist_shapes_and_determinism():
    (x1, y1), (xt1, yt1) = datasets.synthetic_mnist(n_train=256, n_test=64)
    (x2, y2), _ = datasets.synthetic_mnist(n_train=256, n_test=64)
    assert x1.shape == (256, 28, 28) and x1.dtype == np.uint8
    assert yt1.shape == (64,)
    np.testing.assert_array_equal(x1, x2)
    np.testing.assert_array_equal(y1, y2)
    assert set(np.unique(y1)) <= set(range(10))


def test_synthetic_cifar_shapes():
    (x, y), (xt, yt) = datasets.synthetic_cifar10(n_train=128, n_test=32)
    assert x.shape == (128, 32, 32, 3) and x.dtype == np.uint8
    assert xt.shape == (32, 32, 32, 3)


def test_synthetic_imdb_padding_and_labels():
    (x, y), _ = datasets.synthetic_imdb(n_train=64, n_test=16, num_words=500, maxlen=50)
    assert x.shape == (64, 50) and x.dtype == np.int32
    assert x.max() < 500
    assert set(np.unique(y)) <= {0, 1}
    # pre-padding: rows start with zeros, end with tokens
    row = x[0]
    nz = np.nonzero(row)[0]
    assert len(nz) > 0 and nz[-1] == 49


def test_loader_prefers_local_cache(tmp_path, monkeypatch):
    monkeypatch.setenv("ELEPHAS_DATA_DIR", str(tmp_path))
    rng = np.random.default_rng(0)
    np.savez(
        tmp_path / "mnist.npz",
        x_train=rng.integers(0, 255, (32, 28, 28), dtype=np.uint8),
        y_train=rng.integers(0, 10, 32),
        x_test=rng.integers(0, 255, (8, 28, 28), dtype=np.uint8),
        y_test=rng.integers(0, 10, 8),
    )
    (xtr, ytr), (xte, yte), real = datasets.load_mnist()
    assert real is True
    assert xtr.shape == (32, 28, 28) and xte.shape == (8, 28, 28)


def test_loader_synthetic_fallback(tmp_path, monkeypatch):
    monkeypatch.setenv("ELEPHAS_DATA_DIR", str(tmp_path / "missing"))
    (_, _), (_, _), real = datasets.load_mnist()
    assert real is False


def test_one_hot():
    y = datasets.one_hot(np.array([0, 2, 1]), 3)
    np.testing.assert_array_equal(y, np.eye(3, dtype=np.float32)[[0, 2, 1]])
