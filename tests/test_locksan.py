"""Runtime lock-sanitizer unit tests.

Pins the two contracts ISSUE 15 cares about: (1) the DISABLED path is
zero-overhead — the factories hand back plain ``threading`` primitives,
checked by type, so production never pays for the instrumentation; (2)
the ENABLED path detects order inversions — including transitive ones
and ones seeded from the statically derived ``ANALYSIS.json`` order —
and raises at the acquisition site instead of deadlocking the process.
Threads here are real: the cross-thread tests establish an order on one
thread and violate it from another.
"""

import json
import threading

import pytest

from elephas_tpu.utils import locksan
from elephas_tpu.utils.locksan import (InstrumentedCondition,
                                       InstrumentedLock, LockOrderInversion,
                                       make_condition, make_lock, make_rlock)
from elephas_tpu.utils.rwlock import RWLock


@pytest.fixture
def sanitizer():
    locksan.enable()
    yield locksan.registry()
    locksan.disable()


def run_in_thread(fn):
    """Run ``fn`` on a fresh thread; re-raise anything it raised."""
    box = {}

    def worker():
        try:
            fn()
        except BaseException as exc:  # noqa: BLE001 - relayed to caller
            box["exc"] = exc

    t = threading.Thread(target=worker, name="locksan-test")
    t.start()
    t.join(timeout=10)
    assert not t.is_alive(), "worker thread hung"
    if "exc" in box:
        raise box["exc"]


# -- disabled path: zero overhead --------------------------------------------


def test_disabled_factories_return_plain_primitives():
    assert not locksan.enabled()
    assert type(make_lock("x")) is type(threading.Lock())
    assert type(make_rlock("x")) is type(threading.RLock())
    assert type(make_condition("x")) is threading.Condition
    # and the module-level blocking hook is a free no-op
    locksan.note_blocking("fsync")
    assert locksan.registry().blocking_events == []


def test_enable_swaps_factories_and_resets_registry(sanitizer):
    assert locksan.enabled()
    assert isinstance(make_lock("x"), InstrumentedLock)
    assert isinstance(make_rlock("x"), InstrumentedLock)
    assert isinstance(make_condition("x"), InstrumentedCondition)
    sanitizer.load_static_order([("p", "q")])
    locksan.enable()  # fresh registry: previous orders must not leak
    assert locksan.registry() is not sanitizer
    assert locksan.registry().snapshot_edges() == {}
    assert locksan.registry()._static == {}


# -- inversion detection -----------------------------------------------------


def test_same_thread_inversion_raises(sanitizer):
    a, b = make_lock("a"), make_lock("b")
    with a:
        with b:
            pass
    with b:
        with pytest.raises(LockOrderInversion, match="a -> b"):
            a.acquire()
    assert sanitizer.checks >= 3


def test_cross_thread_inversion_raises(sanitizer):
    a, b = make_lock("a"), make_lock("b")

    def establish():
        with a:
            with b:
                pass

    def invert():
        with b:
            a.acquire()

    run_in_thread(establish)
    with pytest.raises(LockOrderInversion, match="inversion"):
        run_in_thread(invert)


def test_transitive_inversion_raises(sanitizer):
    a, b, c = make_lock("a"), make_lock("b"), make_lock("c")
    with a, b:
        pass
    with b, c:
        pass
    with c:
        with pytest.raises(LockOrderInversion, match="a -> b -> c"):
            a.acquire()


def test_consistent_order_never_raises(sanitizer):
    a, b = make_lock("a"), make_lock("b")

    def ordered():
        with a:
            with b:
                pass

    for _ in range(3):
        run_in_thread(ordered)
    assert sanitizer.snapshot_edges() == {"a": {"b"}}


def test_static_order_seeding(sanitizer):
    """An inversion against the STATIC order fires on first execution —
    no prior dynamic observation needed."""
    sanitizer.load_static_order([("p", "q")])
    p, q = make_lock("p"), make_lock("q")
    with q:
        with pytest.raises(LockOrderInversion):
            p.acquire()


def test_load_analysis_json(tmp_path):
    art = tmp_path / "ANALYSIS.json"
    art.write_text(json.dumps({
        "lock_graph": {"edges": [{"src": "p", "dst": "q",
                                  "path": "x.py", "lineno": 1}]}}))
    locksan.enable(analysis_path=art)
    try:
        with make_lock("q"):
            with pytest.raises(LockOrderInversion):
                make_lock("p").acquire()
    finally:
        locksan.disable()


def test_load_analysis_missing_file_is_tolerated(sanitizer):
    assert sanitizer.load_analysis("/nonexistent/ANALYSIS.json") == 0


def test_self_deadlock_raises(sanitizer):
    lk = make_lock("solo")
    lk.acquire()
    with pytest.raises(LockOrderInversion, match="self-deadlock"):
        lk.acquire()


def test_rlock_reentry_allowed(sanitizer):
    lk = make_rlock("re")
    with lk:
        with lk:
            assert sanitizer.held() == ["re", "re"]
    assert sanitizer.held() == []


def test_nonblocking_acquire_is_exempt(sanitizer):
    a, b = make_lock("a"), make_lock("b")
    with a, b:
        pass
    with b:
        assert a.acquire(blocking=False)  # would raise if order-checked
        a.release()
    # and it adds no edge that would poison later checks
    assert "b" not in sanitizer.snapshot_edges()


def test_timed_acquire_failure_leaves_clean_stack(sanitizer):
    lk = make_lock("held-elsewhere")
    grabbed = threading.Event()
    done = threading.Event()

    def holder():
        with lk._inner:
            grabbed.set()
            done.wait(timeout=10)

    t = threading.Thread(target=holder)
    t.start()
    grabbed.wait(timeout=10)
    assert lk.acquire(timeout=0.05) is False
    assert sanitizer.held() == []
    done.set()
    t.join(timeout=10)


# -- condition / blocking events ---------------------------------------------


def test_condition_wait_notify_roundtrip(sanitizer):
    cond = make_condition("C.cond")
    ready = []

    def consumer():
        with cond:
            while not ready:
                cond.wait(timeout=10)

    t = threading.Thread(target=consumer)
    t.start()
    with cond:
        ready.append(1)
        cond.notify()
    t.join(timeout=10)
    assert not t.is_alive()
    # own lock is excluded: waiting on your own cond is not a finding
    assert sanitizer.blocking_events == []
    assert sanitizer.held() == []


def test_condition_wait_under_foreign_lock_is_recorded(sanitizer):
    outer = make_lock("outer")
    cond = make_condition("C.cond")
    with outer:
        with cond:
            cond.wait(timeout=0.01)
    held, desc, _thread = sanitizer.blocking_events[0]
    assert held == ("outer",)
    assert "C.cond" in desc


def test_note_blocking_records_held_stack(sanitizer):
    with make_lock("j"):
        locksan.note_blocking("journal fsync")
    locksan.note_blocking("idle fsync")  # nothing held: not an event
    assert sanitizer.blocking_events == [
        (("j",), "journal fsync", "MainThread")]


# -- RWLock integration ------------------------------------------------------


def test_rwlock_is_one_graph_node(sanitizer):
    rw = RWLock(name="Buf._lock")
    aux = make_lock("aux")
    with rw.reading():
        with aux:
            pass
    with aux:
        with pytest.raises(LockOrderInversion):
            rw.acquire_write()


def test_rwlock_nested_reads_are_reentrant(sanitizer):
    rw = RWLock(name="Buf._lock")
    with rw.reading():
        with rw.reading():
            pass
    assert sanitizer.held() == []


def test_rwlock_write_after_read_same_thread_raises(sanitizer):
    rw = RWLock(name="Buf._lock")
    rw.acquire_read()
    with pytest.raises(LockOrderInversion, match="self-deadlock"):
        rw.acquire_write()
    rw.release()


def test_unnamed_rwlock_is_untracked(sanitizer):
    rw = RWLock()
    with rw.writing():
        assert sanitizer.held() == []
