"""LM decode throughput bench: KV-cache generate vs no-cache re-forward,
plus the serving engine end-to-end (continuous batching over the slot
pool).

Emits one JSON object per measurement so the numbers land as a committed
artifact (``--out BENCH_DECODE.json``):

- ``{"mode": "cache" | "no_cache", "batch": B, ...}`` — tokens/sec of
  batch-B greedy decode. EVERY row carries ``flops_per_token`` (from
  ``metrics.flops.transformer_flops_per_token``) so the achieved-FLOPs
  math is reproducible from the artifact alone, and ``mfu`` when the
  chip's peak FLOPs are known (None on CPU — see
  ``metrics.flops.peak_flops``),
- ``{"mode": "serving", "pipeline": bool, ...}`` — the
  ``InferenceEngine`` driven over a mixed-length workload with
  mid-decode admission, one arm per scheduler mode (unpipelined
  reference vs one-step-lookahead), so the artifact shows the
  before/after of pipelining directly; reports engine tokens/sec, TTFT,
  dispatch→fetch overlap, prefill/decode compile counts. The serving
  arms also land in their own artifact via ``--serve-out
  BENCH_SERVE.json``.

Importable (and runnable with tiny defaults) without a TPU — tier-1
collects it; real numbers come from the dev chip.

Usage: python scripts/lm_bench.py [--batches 1 8 32] [--new 64]
       [--out BENCH_DECODE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(vocab: int, d_model: int, heads: int, layers: int,
                max_seq: int):
    import jax.numpy as jnp

    from elephas_tpu.api.compile import CompiledModel
    from elephas_tpu.models.transformer import TransformerLM

    module = TransformerLM(
        vocab_size=vocab, d_model=d_model, num_heads=heads,
        num_layers=layers, max_seq_len=max_seq,
    )
    return CompiledModel(
        module,
        optimizer="adam",
        loss="sparse_categorical_crossentropy",
        input_shape=(16,),
        input_dtype=jnp.int32,
    )


def flops_per_decode_token(compiled, context_len: int) -> float:
    from elephas_tpu.metrics import transformer_flops_per_token

    m = compiled.module
    return transformer_flops_per_token(
        compiled.count_params(), m.num_layers, m.d_model, context_len
    )


def bench_generate(compiled, batch: int, prompt_len: int, new_tokens: int,
                   use_cache: bool, reps: int) -> dict:
    """Tokens/sec of batch-B greedy decode, cache vs no-cache."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from elephas_tpu.metrics import mfu
    from elephas_tpu.models.transformer import generate

    rng = np.random.default_rng(0)
    vocab = compiled.module.vocab_size
    prompt = rng.integers(1, vocab, (batch, prompt_len)).astype(np.int32)

    if use_cache:
        run = lambda: generate(compiled, prompt, new_tokens)  # noqa: E731
    else:
        # No-cache baseline: re-forward the growing sequence per token
        # (the quadratic loop KV caching exists to remove).
        fwd = jax.jit(
            lambda params, toks: compiled.module.apply(
                {"params": params}, toks
            )
        )

        def run():
            toks = jnp.asarray(prompt)
            for _ in range(new_tokens):
                logits = fwd(compiled.params, toks)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
            return toks

    jax.block_until_ready(run())  # compile outside the timed region
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    tps = batch * new_tokens / dt
    fpt = flops_per_decode_token(compiled, prompt_len + new_tokens)
    return {
        "mode": "cache" if use_cache else "no_cache",
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "sec_per_rep": dt,
        "tokens_per_sec": tps,
        "flops_per_token": fpt,
        "mfu": mfu(tps, fpt),
    }


def bench_serving(compiled, max_slots: int, prompt_len: int,
                  new_tokens: int, requests: int,
                  pipeline: bool = True, tracer=None) -> dict:
    """Drive the InferenceEngine over a mixed-length workload: more
    requests than slots, staggered submits, so admission happens
    mid-decode (continuous batching) and slots get reused.
    ``pipeline=False`` runs the unpipelined reference scheduler — the
    before/after pair is the pipelining speedup. ``tracer``: an
    ``obs.Tracer`` to record the run's span tree into (None = the
    disabled default — the untraced baseline)."""
    import numpy as np

    from elephas_tpu.metrics import mfu
    from elephas_tpu.serving import InferenceEngine

    rng = np.random.default_rng(1)
    vocab = compiled.module.vocab_size
    engine = InferenceEngine(
        compiled,
        max_slots=max_slots,
        max_prompt_len=prompt_len,
        max_len=prompt_len + new_tokens + 1,
        queue_depth=max(requests, 1),
        pipeline=pipeline,
        tracer=tracer,
    )
    # Warm all three compiled programs (prefill, slot admission, decode)
    # outside the timed region — bench_generate does the same with its
    # untimed first run. Serving tok/s measures serving, not XLA
    # compile time.
    engine.result(engine.submit([1] * prompt_len, max_new_tokens=2))
    engine.metrics.reset()
    t0 = time.perf_counter()
    rids = []
    for i in range(requests):
        plen = int(rng.integers(1, prompt_len + 1))
        prompt = rng.integers(1, vocab, plen).tolist()
        rids.append(engine.submit(prompt, max_new_tokens=new_tokens))
        # Stagger: keep the pool busy while later requests arrive.
        if len(rids) >= max_slots:
            engine.step()
    results = [engine.result(r) for r in rids]
    dt = time.perf_counter() - t0
    stats = engine.stats()
    tps = stats["tokens_out"] / dt
    fpt = flops_per_decode_token(compiled, prompt_len + new_tokens)
    return {
        "mode": "serving",
        "pipeline": pipeline,
        "max_slots": max_slots,
        "requests": requests,
        "completed": stats["completed"],
        "tokens_out": stats["tokens_out"],
        "wall_sec": dt,
        "tokens_per_sec": tps,
        "flops_per_token": fpt,
        "mfu": mfu(tps, fpt),
        "ttft_s_avg": stats["ttft_s_avg"],
        "itl_s_avg": stats["itl_s_avg"],
        "dispatch_to_fetch_s_avg": stats["dispatch_to_fetch_s_avg"],
        # Tail latencies from the ServingMetrics histograms: the SLO
        # columns (means hide stall spikes).
        **{
            f"{base}_{p}": stats[f"{base}_{p}"]
            for base in ("ttft_s", "itl_s", "dispatch_to_fetch_s")
            for p in ("p50", "p95", "p99")
        },
        "prefill_traces": stats["prefill_traces"],
        "decode_traces": stats["decode_traces"],
        "pool_admitted_total": stats["pool_admitted_total"],
        "all_completed": all(r.status == "completed" for r in results),
    }


def bench_trace_overhead(compiled, max_slots: int, prompt_len: int,
                         new_tokens: int, requests: int,
                         rounds: int = 3, attempts: int = 3) -> dict:
    """Guardrail: tracing must cost < 2% serving throughput.

    The tracer's pitch is "leave it on in production", so the bench
    enforces it: one DISCARDED warmup run (the first run after a compile
    reads measurably fast — hot caches), then ``rounds`` traced/untraced
    pairs whose within-pair order alternates (decorrelates drift —
    thermal, page cache — from the arm), compared best-of-``rounds``
    (the noise floor on shared CPU runners swamps a 2% signal in means).
    Retries the whole measurement before the assert fires; a persistent
    > 2% gap is a real regression in the record/instant hot path."""
    from elephas_tpu.obs import Tracer

    run = lambda tracer: bench_serving(  # noqa: E731
        compiled, max_slots, prompt_len, new_tokens, requests,
        pipeline=True, tracer=tracer,
    )["tokens_per_sec"]
    run(None)  # warmup, discarded
    for attempt in range(attempts):
        plain, traced = [], []
        for r in range(rounds):
            if r % 2 == 0:
                plain.append(run(None))
                traced.append(run(Tracer()))
            else:
                traced.append(run(Tracer()))
                plain.append(run(None))
        overhead = 1.0 - max(traced) / max(plain)
        if overhead < 0.02:
            break
    rec = {
        "mode": "serving_trace_overhead",
        "rounds": rounds,
        "attempts_used": attempt + 1,
        "tokens_per_sec_untraced": max(plain),
        "tokens_per_sec_traced": max(traced),
        "overhead_pct": overhead * 100.0,
        "within_2pct": overhead < 0.02,
    }
    assert rec["within_2pct"], (
        f"tracing overhead {overhead * 100.0:.2f}% >= 2% after "
        f"{attempts} attempts (traced {max(traced):.0f} vs untraced "
        f"{max(plain):.0f} tok/s)"
    )
    return rec


def bench_slo(compiled, max_slots: int, prompt_len: int, new_tokens: int,
              requests: int, probes: int = 3, rounds: int = 3,
              attempts: int = 3) -> dict:
    """Goodput + canary arm: serve the standard mixed workload with
    blackbox canary probes riding the real submit path, and commit both
    the SLO attainment (per-objective goodput ratios, canary-excluded
    by construction) and the canary's own blackbox SLIs. Probe cost is
    measured with the tracing-guardrail discipline — a discarded
    warmup, then ``rounds`` canaried/plain pairs with alternating
    within-pair order, compared best-of-rounds on *real-traffic*
    tokens/sec — and gated under 2% by scripts/bench_gate.py."""
    import numpy as np

    from elephas_tpu.obs.canary import CanaryDriver
    from elephas_tpu.serving import InferenceEngine

    vocab = compiled.module.vocab_size

    def run(canaried: bool):
        rng = np.random.default_rng(1)
        engine = InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=max(requests, 1) + probes,
            pipeline=True,
        )
        driver = CanaryDriver(engine) if canaried else None
        engine.result(engine.submit([1] * prompt_len, max_new_tokens=2))
        engine.metrics.reset()
        # Probes fire spread through the submit schedule so they share
        # the batch with real traffic (the realistic interference case).
        probe_at = {max(1, (i + 1) * requests // (probes + 1))
                    for i in range(probes)} if canaried else set()
        t0 = time.perf_counter()
        rids = []
        for i in range(requests):
            plen = int(rng.integers(1, prompt_len + 1))
            prompt = rng.integers(1, vocab, plen).tolist()
            rids.append(engine.submit(prompt, max_new_tokens=new_tokens))
            if len(rids) >= max_slots:
                engine.step()
            if i in probe_at:
                driver.probe()
        results = [engine.result(r) for r in rids]
        dt = time.perf_counter() - t0
        real_tokens = sum(len(r.tokens) for r in results)
        return real_tokens / dt, engine, driver, results

    run(False)  # warmup, discarded
    for attempt in range(attempts):
        plain, canaried = [], []
        for r in range(rounds):
            if r % 2 == 0:
                plain.append(run(False)[0])
                canaried.append(run(True))
            else:
                canaried.append(run(True))
                plain.append(run(False)[0])
        overhead = 1.0 - max(c[0] for c in canaried) / max(plain)
        if overhead < 0.02:
            break
    best = max(canaried, key=lambda c: c[0])
    _, engine, driver, results = best
    slo = engine.slo.snapshot()
    probe_doc = driver.snapshot()
    return {
        "mode": "serving_slo",
        "pipeline": True,
        "max_slots": max_slots,
        "requests": requests,
        "evaluated": slo["evaluated"],
        "goodput": slo["goodput"]["lifetime"],
        "goodput_ratio": slo["goodput_ratio"],
        "canary_probes": probe_doc["probes"],
        "canary_failures": probe_doc["failures"],
        "canary_e2e_s_avg": probe_doc["e2e_s_avg"],
        "canary_e2e_s_max": probe_doc["e2e_s_max"],
        "tokens_per_sec_plain": max(plain),
        "tokens_per_sec_canaried": max(c[0] for c in canaried),
        "canary_overhead_pct": overhead * 100.0,
        "within_2pct": overhead < 0.02,
        "attempts_used": attempt + 1,
        "rounds": rounds,
        "all_completed": all(r.status == "completed" for r in results),
    }


def main(argv=None) -> list:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batches", type=int, nargs="+", default=[1, 8, 32])
    parser.add_argument("--prompt-len", type=int, default=32)
    parser.add_argument("--new", type=int, default=64)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--vocab", type=int, default=512)
    parser.add_argument("--d-model", type=int, default=128)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--serving-slots", type=int, default=4)
    parser.add_argument("--serving-requests", type=int, default=12)
    parser.add_argument("--out", type=str, default=None,
                        help="also write records as a JSON array")
    parser.add_argument("--serve-out", type=str, default=None,
                        help="write the serving arms (before/after "
                             "pipelining) as their own JSON artifact")
    parser.add_argument("--trace", type=str, default=None,
                        help="record one traced pipelined serving run's "
                             "span tree to this Chrome trace JSON, plus a "
                             "trace_report.py summary next to it (.md)")
    parser.add_argument("--no-overhead-check", action="store_true",
                        help="skip the traced-vs-untraced < 2%% guardrail "
                             "(6 extra serving runs)")
    parser.add_argument("--slo", action="store_true",
                        help="run the goodput + blackbox-canary arm "
                             "(SLO attainment ratios, canary probe SLIs, "
                             "and the canaried-vs-plain < 2%% overhead "
                             "measurement)")
    args = parser.parse_args(argv)

    import jax

    compiled = build_model(
        args.vocab, args.d_model, args.heads, args.layers,
        max_seq=args.prompt_len + args.new + 1,
    )
    records = [{
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "params": compiled.count_params(),
        "d_model": args.d_model,
        "layers": args.layers,
    }]
    for batch in args.batches:
        for use_cache in (True, False):
            rec = bench_generate(
                compiled, batch, args.prompt_len, args.new, use_cache,
                args.reps,
            )
            records.append(rec)
            print(json.dumps(rec))
    serving_records = []
    for pipeline in (False, True):  # reference first, then the hot path
        rec = bench_serving(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests, pipeline=pipeline,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if not args.no_overhead_check:
        rec = bench_trace_overhead(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.slo:
        rec = bench_slo(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.trace:
        from elephas_tpu.obs import Tracer

        import scripts.trace_report as trace_report

        tracer = Tracer()
        bench_serving(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests, pipeline=True, tracer=tracer,
        )
        tracer.export_chrome(args.trace)
        report_path = os.path.splitext(args.trace)[0] + ".md"
        text = trace_report.report(args.trace)
        with open(report_path, "w") as f:
            f.write(text)
        print(f"trace: {args.trace} (Perfetto-viewable); report: "
              f"{report_path}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)
    if args.serve_out:
        with open(args.serve_out, "w") as f:
            json.dump([records[0], *serving_records], f, indent=1)
    return records


if __name__ == "__main__":
    main()
