"""LM decode throughput bench: KV-cache generate vs no-cache re-forward,
plus the serving engine end-to-end (continuous batching over the slot
pool).

Emits one JSON object per measurement so the numbers land as a committed
artifact (``--out BENCH_DECODE.json``):

- ``{"mode": "cache" | "no_cache", "batch": B, ...}`` — tokens/sec of
  batch-B greedy decode. EVERY row carries ``flops_per_token`` (from
  ``metrics.flops.transformer_flops_per_token``) so the achieved-FLOPs
  math is reproducible from the artifact alone, and ``mfu`` when the
  chip's peak FLOPs are known (None on CPU — see
  ``metrics.flops.peak_flops``),
- ``{"mode": "serving", "pipeline": bool, ...}`` — the
  ``InferenceEngine`` driven over a mixed-length workload with
  mid-decode admission, one arm per scheduler mode (unpipelined
  reference vs one-step-lookahead), so the artifact shows the
  before/after of pipelining directly; reports engine tokens/sec, TTFT,
  dispatch→fetch overlap, prefill/decode compile counts. The serving
  arms also land in their own artifact via ``--serve-out
  BENCH_SERVE.json``,
- ``{"mode": "serving_spec", ...}`` (``--spec``) — speculative
  draft-and-verify decode vs the unspeculated oracle on a
  shared-prefix workload: accept rate, realized tokens/step, the
  per-token spec/plain ITL ratio, token identity, and the
  compile-counter pins (one draft + one verify program), with draft
  params delivered by a real 2-shard parameter-server group,
- ``{"mode": "fleet_*", ...}`` (``--fleet`` → ``--fleet-out
  BENCH_FLEET.json``) — the replicated fleet: routed-vs-bare overhead
  with token-identity proof, N-replica session-affinity throughput,
  the kill-a-replica-mid-traffic chaos arm (fleet-plane outage arc,
  blackbox canary outage, goodput dip, requeue recovery), and the
  autoscaler's seeded decision replay. Gated by scripts/bench_gate.py
  ``--fleet``,
- ``{"mode": "fleet_disagg", ...}`` (``--disagg``, appends to the
  fleet artifact) — disaggregated prefill/decode tiers vs a monolithic
  fleet on the same two-tenant interference workload: token identity
  across the KV-block handoff, the decode-tier ITL p99 ratio under
  long-prompt interference, handoff latency p50/p99, the cross-tier
  prefix hit rate, and the per-tenant fair-share goodput floor.
- ``{"mode": "fleet_rollout", ...}`` (``--rollout``, appends to the
  fleet artifact) — live model delivery: mid-stream zero-delta swap
  identity + swap-tax ITL ratio, steady-state subscription wire cost,
  and a full canary arc (live trainer push → promote, then a forced
  rollback with zero non-canary exposure to the poisoned version).

Importable (and runnable with tiny defaults) without a TPU — tier-1
collects it; real numbers come from the dev chip.

Usage: python scripts/lm_bench.py [--batches 1 8 32] [--new 64]
       [--out BENCH_DECODE.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(vocab: int, d_model: int, heads: int, layers: int,
                max_seq: int):
    import jax.numpy as jnp

    from elephas_tpu.api.compile import CompiledModel
    from elephas_tpu.models.transformer import TransformerLM

    module = TransformerLM(
        vocab_size=vocab, d_model=d_model, num_heads=heads,
        num_layers=layers, max_seq_len=max_seq,
    )
    return CompiledModel(
        module,
        optimizer="adam",
        loss="sparse_categorical_crossentropy",
        input_shape=(16,),
        input_dtype=jnp.int32,
    )


def flops_per_decode_token(compiled, context_len: int) -> float:
    from elephas_tpu.metrics import transformer_flops_per_token

    m = compiled.module
    return transformer_flops_per_token(
        compiled.count_params(), m.num_layers, m.d_model, context_len
    )


def bench_generate(compiled, batch: int, prompt_len: int, new_tokens: int,
                   use_cache: bool, reps: int) -> dict:
    """Tokens/sec of batch-B greedy decode, cache vs no-cache."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from elephas_tpu.metrics import mfu
    from elephas_tpu.models.transformer import generate

    rng = np.random.default_rng(0)
    vocab = compiled.module.vocab_size
    prompt = rng.integers(1, vocab, (batch, prompt_len)).astype(np.int32)

    if use_cache:
        run = lambda: generate(compiled, prompt, new_tokens)  # noqa: E731
    else:
        # No-cache baseline: re-forward the growing sequence per token
        # (the quadratic loop KV caching exists to remove).
        fwd = jax.jit(
            lambda params, toks: compiled.module.apply(
                {"params": params}, toks
            )
        )

        def run():
            toks = jnp.asarray(prompt)
            for _ in range(new_tokens):
                logits = fwd(compiled.params, toks)
                nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
                toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
            return toks

    jax.block_until_ready(run())  # compile outside the timed region
    t0 = time.perf_counter()
    for _ in range(reps):
        out = run()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / reps
    tps = batch * new_tokens / dt
    fpt = flops_per_decode_token(compiled, prompt_len + new_tokens)
    return {
        "mode": "cache" if use_cache else "no_cache",
        "batch": batch,
        "prompt_len": prompt_len,
        "new_tokens": new_tokens,
        "sec_per_rep": dt,
        "tokens_per_sec": tps,
        "flops_per_token": fpt,
        "mfu": mfu(tps, fpt),
    }


def bench_serving(compiled, max_slots: int, prompt_len: int,
                  new_tokens: int, requests: int,
                  pipeline: bool = True, tracer=None) -> dict:
    """Drive the InferenceEngine over a mixed-length workload: more
    requests than slots, staggered submits, so admission happens
    mid-decode (continuous batching) and slots get reused.
    ``pipeline=False`` runs the unpipelined reference scheduler — the
    before/after pair is the pipelining speedup. ``tracer``: an
    ``obs.Tracer`` to record the run's span tree into (None = the
    disabled default — the untraced baseline)."""
    import numpy as np

    from elephas_tpu.metrics import mfu
    from elephas_tpu.serving import InferenceEngine

    rng = np.random.default_rng(1)
    vocab = compiled.module.vocab_size
    engine = InferenceEngine(
        compiled,
        max_slots=max_slots,
        max_prompt_len=prompt_len,
        max_len=prompt_len + new_tokens + 1,
        queue_depth=max(requests, 1),
        pipeline=pipeline,
        tracer=tracer,
    )
    # Warm all three compiled programs (prefill, slot admission, decode)
    # outside the timed region — bench_generate does the same with its
    # untimed first run. Serving tok/s measures serving, not XLA
    # compile time.
    engine.result(engine.submit([1] * prompt_len, max_new_tokens=2))
    engine.metrics.reset()
    t0 = time.perf_counter()
    rids = []
    for i in range(requests):
        plen = int(rng.integers(1, prompt_len + 1))
        prompt = rng.integers(1, vocab, plen).tolist()
        rids.append(engine.submit(prompt, max_new_tokens=new_tokens))
        # Stagger: keep the pool busy while later requests arrive.
        if len(rids) >= max_slots:
            engine.step()
    results = [engine.result(r) for r in rids]
    dt = time.perf_counter() - t0
    stats = engine.stats()
    tps = stats["tokens_out"] / dt
    fpt = flops_per_decode_token(compiled, prompt_len + new_tokens)
    return {
        "mode": "serving",
        "pipeline": pipeline,
        "max_slots": max_slots,
        "requests": requests,
        "completed": stats["completed"],
        "tokens_out": stats["tokens_out"],
        "wall_sec": dt,
        "tokens_per_sec": tps,
        "flops_per_token": fpt,
        "mfu": mfu(tps, fpt),
        "ttft_s_avg": stats["ttft_s_avg"],
        "itl_s_avg": stats["itl_s_avg"],
        "dispatch_to_fetch_s_avg": stats["dispatch_to_fetch_s_avg"],
        # Tail latencies from the ServingMetrics histograms: the SLO
        # columns (means hide stall spikes).
        **{
            f"{base}_{p}": stats[f"{base}_{p}"]
            for base in ("ttft_s", "itl_s", "dispatch_to_fetch_s")
            for p in ("p50", "p95", "p99")
        },
        "prefill_traces": stats["prefill_traces"],
        "decode_traces": stats["decode_traces"],
        "pool_admitted_total": stats["pool_admitted_total"],
        "all_completed": all(r.status == "completed" for r in results),
    }


def bench_trace_overhead(compiled, max_slots: int, prompt_len: int,
                         new_tokens: int, requests: int,
                         rounds: int = 3, attempts: int = 3) -> dict:
    """Guardrail: tracing must cost < 2% serving throughput.

    The tracer's pitch is "leave it on in production", so the bench
    enforces it: one DISCARDED warmup run (the first run after a compile
    reads measurably fast — hot caches), then ``rounds`` traced/untraced
    pairs whose within-pair order alternates (decorrelates drift —
    thermal, page cache — from the arm), compared best-of-``rounds``
    (the noise floor on shared CPU runners swamps a 2% signal in means).
    Retries the whole measurement before the assert fires; a persistent
    > 2% gap is a real regression in the record/instant hot path."""
    from elephas_tpu.obs import Tracer

    run = lambda tracer: bench_serving(  # noqa: E731
        compiled, max_slots, prompt_len, new_tokens, requests,
        pipeline=True, tracer=tracer,
    )["tokens_per_sec"]
    run(None)  # warmup, discarded
    for attempt in range(attempts):
        plain, traced = [], []
        for r in range(rounds):
            if r % 2 == 0:
                plain.append(run(None))
                traced.append(run(Tracer()))
            else:
                traced.append(run(Tracer()))
                plain.append(run(None))
        overhead = 1.0 - max(traced) / max(plain)
        if overhead < 0.02:
            break
    rec = {
        "mode": "serving_trace_overhead",
        "rounds": rounds,
        "attempts_used": attempt + 1,
        "tokens_per_sec_untraced": max(plain),
        "tokens_per_sec_traced": max(traced),
        "overhead_pct": overhead * 100.0,
        "within_2pct": overhead < 0.02,
    }
    assert rec["within_2pct"], (
        f"tracing overhead {overhead * 100.0:.2f}% >= 2% after "
        f"{attempts} attempts (traced {max(traced):.0f} vs untraced "
        f"{max(plain):.0f} tok/s)"
    )
    return rec


def bench_store_overhead(compiled, max_slots: int, prompt_len: int,
                         new_tokens: int, requests: int,
                         rounds: int = 3, attempts: int = 3) -> dict:
    """Guardrail: the durable telemetry store must cost < 2% serving
    throughput (the post-mortem plane's pitch is "persist everything,
    pay nothing on the hot path").

    Both arms mount the ops endpoint — history sampler ticking on its
    daemon thread, alert engine scrapable — so the ONLY difference in
    the measured arm is a mounted ``obs.TelemetryStore``: every sampler
    tick, flight note, and alert transition journals to disk (write +
    flush per record). Same discipline as the trace/canary overhead
    gates: discarded warmup, alternating within-pair order, best-of-
    ``rounds``, whole-measurement retries before the assert fires."""
    import tempfile

    import numpy as np

    from elephas_tpu.serving import InferenceEngine

    vocab = compiled.module.vocab_size

    def run(store_dir):
        rng = np.random.default_rng(1)
        engine = InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=max(requests, 1),
            pipeline=True,
        )
        engine.mount_ops(port=0, store_dir=store_dir)
        try:
            engine.result(engine.submit([1] * prompt_len,
                                        max_new_tokens=2))
            t0 = time.perf_counter()
            rids = []
            for i in range(requests):
                plen = int(rng.integers(1, prompt_len + 1))
                prompt = rng.integers(1, vocab, plen).tolist()
                rids.append(engine.submit(prompt,
                                          max_new_tokens=new_tokens))
                if len(rids) >= max_slots:
                    engine.step()
            results = [engine.result(r) for r in rids]
            dt = time.perf_counter() - t0
            tokens = sum(len(r.tokens) for r in results)
            journaled = (engine.store.stats()["records"]
                         if engine.store is not None else 0)
            return tokens / dt, journaled
        finally:
            engine.unmount_ops()

    with tempfile.TemporaryDirectory() as root:
        dirs = iter(range(10000))  # fresh store dir per measured run

        def on():
            return run(os.path.join(root, f"s{next(dirs)}", "telemetry"))

        run(None)  # warmup, discarded
        for attempt in range(attempts):
            plain, stored = [], []
            for r in range(rounds):
                if r % 2 == 0:
                    plain.append(run(None)[0])
                    stored.append(on())
                else:
                    stored.append(on())
                    plain.append(run(None)[0])
            overhead = 1.0 - max(s[0] for s in stored) / max(plain)
            if overhead < 0.02:
                break
    rec = {
        "mode": "serving_store_overhead",
        "rounds": rounds,
        "attempts_used": attempt + 1,
        "tokens_per_sec_unstored": max(plain),
        "tokens_per_sec_stored": max(s[0] for s in stored),
        "journaled_records": max(s[1] for s in stored),
        "overhead_pct": overhead * 100.0,
        "within_2pct": overhead < 0.02,
    }
    assert rec["within_2pct"], (
        f"telemetry store overhead {overhead * 100.0:.2f}% >= 2% after "
        f"{attempts} attempts (stored {rec['tokens_per_sec_stored']:.0f} "
        f"vs unstored {rec['tokens_per_sec_unstored']:.0f} tok/s)"
    )
    return rec


def bench_slo(compiled, max_slots: int, prompt_len: int, new_tokens: int,
              requests: int, probes: int = 3, rounds: int = 3,
              attempts: int = 3) -> dict:
    """Goodput + canary arm: serve the standard mixed workload with
    blackbox canary probes riding the real submit path, and commit both
    the SLO attainment (per-objective goodput ratios, canary-excluded
    by construction) and the canary's own blackbox SLIs. Probe cost is
    measured with the tracing-guardrail discipline — a discarded
    warmup, then ``rounds`` canaried/plain pairs with alternating
    within-pair order, compared best-of-rounds on *real-traffic*
    tokens/sec — and gated under 2% by scripts/bench_gate.py."""
    import numpy as np

    from elephas_tpu.obs.canary import CanaryDriver
    from elephas_tpu.serving import InferenceEngine

    vocab = compiled.module.vocab_size

    def run(canaried: bool):
        rng = np.random.default_rng(1)
        engine = InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=max(requests, 1) + probes,
            pipeline=True,
        )
        driver = CanaryDriver(engine) if canaried else None
        engine.result(engine.submit([1] * prompt_len, max_new_tokens=2))
        engine.metrics.reset()
        # Probes fire spread through the submit schedule so they share
        # the batch with real traffic (the realistic interference case).
        probe_at = {max(1, (i + 1) * requests // (probes + 1))
                    for i in range(probes)} if canaried else set()
        t0 = time.perf_counter()
        rids = []
        for i in range(requests):
            plen = int(rng.integers(1, prompt_len + 1))
            prompt = rng.integers(1, vocab, plen).tolist()
            rids.append(engine.submit(prompt, max_new_tokens=new_tokens))
            if len(rids) >= max_slots:
                engine.step()
            if i in probe_at:
                driver.probe()
        results = [engine.result(r) for r in rids]
        dt = time.perf_counter() - t0
        real_tokens = sum(len(r.tokens) for r in results)
        return real_tokens / dt, engine, driver, results

    run(False)  # warmup, discarded
    for attempt in range(attempts):
        plain, canaried = [], []
        for r in range(rounds):
            if r % 2 == 0:
                plain.append(run(False)[0])
                canaried.append(run(True))
            else:
                canaried.append(run(True))
                plain.append(run(False)[0])
        overhead = 1.0 - max(c[0] for c in canaried) / max(plain)
        if overhead < 0.02:
            break
    best = max(canaried, key=lambda c: c[0])
    _, engine, driver, results = best
    slo = engine.slo.snapshot()
    probe_doc = driver.snapshot()
    return {
        "mode": "serving_slo",
        "pipeline": True,
        "max_slots": max_slots,
        "requests": requests,
        "evaluated": slo["evaluated"],
        "goodput": slo["goodput"]["lifetime"],
        "goodput_ratio": slo["goodput_ratio"],
        "canary_probes": probe_doc["probes"],
        "canary_failures": probe_doc["failures"],
        "canary_e2e_s_avg": probe_doc["e2e_s_avg"],
        "canary_e2e_s_max": probe_doc["e2e_s_max"],
        "tokens_per_sec_plain": max(plain),
        "tokens_per_sec_canaried": max(c[0] for c in canaried),
        "canary_overhead_pct": overhead * 100.0,
        "within_2pct": overhead < 0.02,
        "attempts_used": attempt + 1,
        "rounds": rounds,
        "all_completed": all(r.status == "completed" for r in results),
    }


def bench_prefix(compiled, max_slots: int, prompt_len: int,
                 new_tokens: int, *, sessions: int = 4, turns: int = 3,
                 attempts: int = 3) -> dict:
    """Paged-pool arm (``--prefix``): the three claims the paged KV
    pool makes, measured on one row.

    1. Prefix economics — multi-turn sessions sharing a system prompt
       on the paged engine: committed hit rate (the gate floors it at
       0.5) and prefill tokens the cache actually skipped.
    2. Correctness — the SAME conversation workload on the contiguous
       (``paged=False``) oracle engine must produce identical token
       streams request-for-request (``token_identical`` is an
       equal-rule in the gate, like the fleet router's).
    3. Chunked prefill — a saturating long-prompt workload (prompts as
       long as the model seats, short decodes, admissions arriving
       faster than prefill drains) run twice: unchunked, every decode
       gap absorbs whole batch-1 prefills; chunked with a one-chunk-
       per-step budget, the per-step stall is bounded at one chunk and
       the backlog moves to the queue (TTFT rises, the deliberate
       trade). The committed ``chunked_itl_ratio`` (chunked ITL p99 /
       unchunked ITL p99) carries an absolute gate ceiling of 1.0 and
       measures ~0.4 here; retried ``attempts`` times because shared
       CI machines jitter the tail.
    """
    import numpy as np

    from elephas_tpu.serving import InferenceEngine

    vocab = compiled.module.vocab_size
    block = max(2, prompt_len // 4)
    sys_prompt = np.random.default_rng(9).integers(
        1, vocab, 2 * block).tolist()

    def make_engine(paged: bool, **kw):
        if paged:
            kw.setdefault("kv_block_size", block)
        return InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=sessions * turns + 3 * max_slots + 2,
            pipeline=True,
            paged=paged,
            **kw,
        )

    def run_conversations(paged: bool):
        eng = make_engine(paged)
        eng.result(eng.submit([1] * prompt_len, max_new_tokens=2))
        eng.metrics.reset()
        rng = np.random.default_rng(13)
        streams = []
        for _turn in range(turns):
            rids = []
            for _s in range(sessions):
                plen = int(rng.integers(
                    1, prompt_len - len(sys_prompt) + 1))
                prompt = sys_prompt + rng.integers(1, vocab, plen).tolist()
                rids.append(eng.submit(prompt, max_new_tokens=new_tokens))
            # Turn barrier: later turns arrive after earlier ones
            # published their prefixes — the repeat-conversation shape.
            streams.extend(
                list(eng.result(r).tokens) for r in rids)
        return streams, eng.stats()

    paged_streams, paged_stats = run_conversations(True)
    oracle_streams, _ = run_conversations(False)
    token_identical = paged_streams == oracle_streams

    itl_new = 4
    long_prompt = compiled.module.max_seq_len - itl_new - 1
    itl_requests = 6 * max_slots

    def run_itl(chunk, per_step):
        eng = InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=long_prompt,
            max_len=long_prompt + itl_new + 1,
            queue_depth=itl_requests + 2,
            pipeline=True,
            paged=True,
            kv_block_size=block,
            prefill_chunk=chunk,
            prefill_chunks_per_step=per_step,
        )
        eng.result(eng.submit([1] * long_prompt, max_new_tokens=2))
        eng.metrics.reset()
        rng = np.random.default_rng(5)
        rids = []
        for _ in range(itl_requests):
            prompt = rng.integers(1, vocab, long_prompt).tolist()
            rids.append(eng.submit(prompt, max_new_tokens=itl_new))
            if len(rids) >= max_slots:
                eng.step()
        results = [eng.result(r, timeout_s=120.0) for r in rids]
        ok = all(r.status == "completed" for r in results)
        st = eng.stats()
        return st["itl_s_p99"], st["ttft_s_p95"], ok

    chunk_w = max(1, min(8, long_prompt // 2))
    for attempt in range(attempts):
        unchunked_p99, unchunked_ttft, ok_u = run_itl(None, None)
        chunked_p99, chunked_ttft, ok_c = run_itl(chunk_w, 1)
        if chunked_p99 <= unchunked_p99:
            break
    return {
        "mode": "serving_prefix",
        "pipeline": True,
        "paged": True,
        "max_slots": max_slots,
        "kv_block_size": block,
        "sessions": sessions,
        "turns": turns,
        "prefix_hits": paged_stats["prefix_hits"],
        "prefix_lookups": paged_stats["prefix_lookups"],
        "prefix_hit_rate": paged_stats["prefix_hit_rate"],
        "prefill_tokens_saved": paged_stats["prefix_tokens_saved"],
        "token_identical": token_identical,
        "prefill_chunk": chunk_w,
        "long_prompt_len": long_prompt,
        "itl_new_tokens": itl_new,
        "itl_requests": itl_requests,
        "itl_s_p99_chunked": chunked_p99,
        "itl_s_p99_unchunked": unchunked_p99,
        "chunked_itl_ratio": (chunked_p99 / unchunked_p99
                              if unchunked_p99 else None),
        # The other side of the trade, committed for honesty: the chunk
        # budget defers prefill work, so queue wait (TTFT) grows while
        # the decode tail shrinks.
        "ttft_s_p95_chunked": chunked_ttft,
        "ttft_s_p95_unchunked": unchunked_ttft,
        "attempts_used": attempt + 1,
        "all_completed": ok_u and ok_c,
    }


def bench_spec(compiled, max_slots: int, prompt_len: int,
               new_tokens: int, *, sessions: int = 4, turns: int = 3,
               gamma: int = 3, refresh_every: int = 8) -> dict:
    """Speculative-decoding arm (``--spec``): draft-and-verify decode on
    the paged engine, measured against the unspeculated oracle on the
    SAME shared-prefix workload.

    The draft model's params are delivered by a real 2-shard parameter
    server group over sockets (``ShardedParameterClient``, version-gated
    pulls bounded by ``refresh_every``) — the PS-delivered-draft bridge,
    exercised end-to-end rather than faked. At bench scale no distilled
    draft checkpoint exists, so the delivered draft carries the target's
    own weights: the committed ``spec_accept_rate`` is the MECHANICAL
    ceiling (a same-weights draft must accept ~everything; the gate
    floor catches draft-cache/rollback breakage, which shows up as
    silently sunk acceptance, not as wrong tokens). Self-draft
    acceptance on this untrained bench model is measured separately in
    PROFILE.md §22 — it needs a trained target to clear the floor.

    Committed claims: ``token_identical`` (spec streams == oracle
    streams, request-for-request — identity is correctness, equal-rule
    in the gate), ``spec_accept_rate`` (floor 0.5), ``tokens_per_step``
    (floor 1.3 — the whole point of speculation), ``spec_itl_ratio``
    (spec mean ITL / plain mean ITL, ceiling 1.0 — speculation must not
    trade the tail away), and the compile counters (exactly one draft +
    one verify program after warmup).
    """
    import numpy as np

    from elephas_tpu.parameter import ShardGroup
    from elephas_tpu.serving import DraftModelSource, InferenceEngine

    m = compiled.module
    vocab = m.vocab_size
    block = max(2, prompt_len // 4)
    # The speculative pool's virtual row extends ``gamma`` columns past
    # max_len (rounded up to a block); the draft model's pos_embed table
    # must cover it, and pos_embed is sized by max_seq_len — so the spec
    # arm builds its own model with that headroom rather than stretching
    # the shared bench model (which would resize every other arm's
    # params).
    compiled = build_model(
        vocab, m.d_model, m.num_heads, m.num_layers,
        max_seq=prompt_len + new_tokens + 1 + gamma + block,
    )
    sys_prompt = np.random.default_rng(9).integers(
        1, vocab, 2 * block).tolist()

    def run(group=None):
        spec = group is not None
        kw = {}
        if spec:
            kw.update(
                speculative=True, gamma=gamma,
                draft_source=DraftModelSource(
                    compiled.module, group.client(),
                    refresh_every=refresh_every,
                ),
            )
        eng = InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=sessions * turns + 2,
            pipeline=True,
            paged=True,
            kv_block_size=block,
            # Model draft sources require prefix_cache=False (a
            # prefix-matched admission would leave the draft cache
            # cold); the oracle matches so the arms differ ONLY in
            # speculation. "Shared prefix" stays a workload shape.
            prefix_cache=False,
            **kw,
        )
        eng.result(eng.submit([1] * prompt_len, max_new_tokens=2))
        eng.metrics.reset()
        rng = np.random.default_rng(13)
        streams, results = [], []
        for _turn in range(turns):
            rids = []
            for _s in range(sessions):
                plen = int(rng.integers(
                    1, prompt_len - len(sys_prompt) + 1))
                prompt = sys_prompt + rng.integers(1, vocab, plen).tolist()
                rids.append(eng.submit(prompt, max_new_tokens=new_tokens))
            for r in rids:
                res = eng.result(r, timeout_s=120.0)
                results.append(res)
                streams.append(list(res.tokens))
        st = eng.stats()
        source = eng.spec.source if spec else None
        return streams, results, st, source

    group = ShardGroup(compiled.params, 2, mode="socket")
    group.start()
    try:
        spec_streams, spec_results, spec_st, source = run(group)
    finally:
        group.stop()
    oracle_streams, oracle_results, plain_st, _ = run(None)
    token_identical = spec_streams == oracle_streams
    # ITL histograms record per-STEP latency (one verify window is one
    # step emitting up to gamma+1 tokens — that's what tokens_per_step
    # disambiguates), so the committed ratio is per emitted TOKEN:
    # spec step cost amortized over its tokens/step, against the plain
    # engine's one-token steps. Below 1.0 means speculation emits
    # tokens faster than plain decode, the claim the gate holds.
    tps = spec_st["spec_tokens_per_step"]
    spec_itl_ratio = (
        (spec_st["itl_s_avg"] / tps) / plain_st["itl_s_avg"]
        if plain_st["itl_s_avg"] and tps else None)
    return {
        "mode": "serving_spec",
        "pipeline": True,
        "paged": True,
        "max_slots": max_slots,
        "requests": sessions * turns,
        "gamma": gamma,
        "draft_source": "model",
        "draft_refresh_every": refresh_every,
        "draft_pulls": source.pulls,
        "spec_windows": spec_st["spec_windows"],
        "spec_accept_rate": spec_st["spec_accept_rate"],
        "tokens_per_step": spec_st["spec_tokens_per_step"],
        "itl_s_p50_spec": spec_st["itl_s_p50"],
        "itl_s_p99_spec": spec_st["itl_s_p99"],
        "itl_s_p50_plain": plain_st["itl_s_p50"],
        "itl_s_p99_plain": plain_st["itl_s_p99"],
        "spec_itl_ratio": spec_itl_ratio,
        "token_identical": token_identical,
        "draft_traces": spec_st["draft_traces"],
        "verify_traces": spec_st["verify_traces"],
        "draft_prefill_traces": spec_st["draft_prefill_traces"],
        "decode_traces_spec": spec_st["decode_traces"],
        "all_completed": all(
            r.status == "completed"
            for r in spec_results + oracle_results),
    }


# -- fleet arms (--fleet → BENCH_FLEET.json) ---------------------------------


def _engine_factory(compiled, max_slots, prompt_len, new_tokens, depth):
    from elephas_tpu.serving import InferenceEngine

    def factory():
        return InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=depth,
            pipeline=True,
        )

    return factory


def _fleet_workload(submit, result, vocab, prompt_len, new_tokens,
                    requests):
    """The standard mixed-length workload against any submit/result
    pair (bare engine or router) — same seed, same prompts, so the two
    arms' token streams are comparable request-for-request."""
    import numpy as np

    rng = np.random.default_rng(1)
    t0 = time.perf_counter()
    rids = []
    for _ in range(requests):
        plen = int(rng.integers(1, prompt_len + 1))
        prompt = rng.integers(1, vocab, plen).tolist()
        rids.append(submit(prompt, new_tokens))
    results = [result(r) for r in rids]
    dt = time.perf_counter() - t0
    tokens = [list(r.tokens) for r in results]
    tps = sum(len(t) for t in tokens) / dt
    return tps, tokens, results


def bench_fleet_routed_vs_bare(compiled, max_slots: int, prompt_len: int,
                               new_tokens: int, requests: int,
                               rounds: int = 3, attempts: int = 3) -> dict:
    """Routing guardrail + correctness proof: a single replica behind
    the router must serve the SAME token streams as a bare engine
    (request-for-request identity) at < 2% throughput cost. Both arms
    run a serve thread (the replica's is built in), so the comparison
    isolates the router hop, not a stepping-discipline difference.
    Measured with the trace-overhead discipline: discarded warmup, then
    ``rounds`` bare/routed pairs with alternating within-pair order,
    compared best-of-rounds, retried ``attempts`` times."""
    import threading

    from elephas_tpu.serving import ReplicaSet, Router

    vocab = compiled.module.vocab_size
    factory = _engine_factory(compiled, max_slots, prompt_len, new_tokens,
                              max(requests, 1) + 1)

    def run_bare():
        engine = factory()
        stop = threading.Event()
        th = threading.Thread(target=engine.serve_forever, args=(stop,),
                              daemon=True)
        th.start()
        engine.result(engine.submit([1] * prompt_len, max_new_tokens=2),
                      timeout_s=60.0)
        out = _fleet_workload(
            lambda p, n: engine.submit(p, max_new_tokens=n),
            lambda r: engine.result(r, timeout_s=120.0),
            vocab, prompt_len, new_tokens, requests)
        stop.set()
        th.join(timeout=10.0)
        return out

    def run_routed():
        rs = ReplicaSet(factory, initial=1)
        router = Router(rs)
        router.result(router.submit([1] * prompt_len, max_new_tokens=2),
                      timeout_s=60.0)
        out = _fleet_workload(
            lambda p, n: router.submit(p, max_new_tokens=n),
            lambda r: router.result(r, timeout_s=120.0),
            vocab, prompt_len, new_tokens, requests)
        router.close()
        return out

    run_bare()  # warmup (compile + caches), discarded
    for attempt in range(attempts):
        bare, routed = [], []
        for r in range(rounds):
            if r % 2 == 0:
                bare.append(run_bare())
                routed.append(run_routed())
            else:
                routed.append(run_routed())
                bare.append(run_bare())
        overhead = 1.0 - (max(x[0] for x in routed)
                          / max(x[0] for x in bare))
        if overhead < 0.02:
            break
    token_identical = all(x[1] == bare[0][1] for x in bare + routed)
    all_completed = all(
        res.status == "completed" for x in bare + routed for res in x[2])
    rec = {
        "mode": "fleet_routed_vs_bare",
        "max_slots": max_slots,
        "requests": requests,
        "rounds": rounds,
        "attempts_used": attempt + 1,
        "tokens_per_sec_bare": max(x[0] for x in bare),
        "tokens_per_sec_routed": max(x[0] for x in routed),
        "routed_overhead_pct": overhead * 100.0,
        "token_identical": token_identical,
        "all_completed": all_completed,
        "within_2pct": overhead < 0.02,
    }
    assert token_identical, "routed token streams diverged from bare engine"
    assert rec["within_2pct"], (
        f"router overhead {overhead * 100.0:.2f}% >= 2% after "
        f"{attempts} attempts"
    )
    return rec


def bench_fleet_n(compiled, max_slots: int, prompt_len: int,
                  new_tokens: int, *, replicas: int = 3,
                  sessions: int = 6, turns: int = 4) -> dict:
    """N-replica steady state: multi-turn sessions through the router.
    Every turn after a session's first should land on the replica
    holding its KV state — the committed ``affinity_hit_rate`` is the
    floor the gate holds (0.9; it measures 1.0 when nothing drains)."""
    import numpy as np

    from elephas_tpu.serving import ReplicaSet, Router

    vocab = compiled.module.vocab_size
    factory = _engine_factory(compiled, max_slots, prompt_len, new_tokens,
                              sessions + replicas)
    rs = ReplicaSet(factory, initial=replicas)
    router = Router(rs)
    # Warm every replica's engine paths (spread by queue pressure).
    warm = [router.submit([1] * prompt_len, max_new_tokens=2)
            for _ in range(2 * replicas)]
    for r in warm:
        router.result(r, timeout_s=60.0)

    rng = np.random.default_rng(7)
    names = [f"s{i}" for i in range(sessions)]
    total_tokens = 0
    results = []
    t0 = time.perf_counter()
    for _turn in range(turns):
        rids = []
        for s in names:
            plen = int(rng.integers(1, prompt_len + 1))
            prompt = rng.integers(1, vocab, plen).tolist()
            rids.append(router.submit(prompt, max_new_tokens=new_tokens,
                                      session=s))
        for r in rids:
            res = router.result(r, timeout_s=120.0)
            results.append(res)
            total_tokens += len(res.tokens)
    dt = time.perf_counter() - t0
    follow_ups = router.affinity_hits + router.affinity_misses
    rec = {
        "mode": "fleet_n3",
        "replicas": replicas,
        "sessions": sessions,
        "turns": turns,
        "requests": sessions * turns,
        "tokens_out": total_tokens,
        "wall_sec": dt,
        "tokens_per_sec": total_tokens / dt,
        "affinity_hits": router.affinity_hits,
        "affinity_misses": router.affinity_misses,
        "affinity_hit_rate": (router.affinity_hits / follow_ups
                              if follow_ups else None),
        "all_completed": all(r.status == "completed" for r in results),
    }
    router.close()
    return rec


def bench_fleet_kill(compiled, max_slots: int, prompt_len: int,
                     new_tokens: int, *, replicas: int = 3) -> dict:
    """Chaos arm: kill a replica mid-traffic and measure the outage
    from three vantage points at once — the fleet plane (the killed
    replica's alive→stale→dead→alive transition arc through real HTTP
    scrapes), the blackbox clients (canary probes routed through the
    fleet during the outage — the router should mask most or all of
    it), and the real goodput ledger (requeued requests pay a bounded
    TTFT hit, they don't fail)."""
    import threading

    from elephas_tpu.obs.fleet import FleetAggregator
    from elephas_tpu.serving import ReplicaSet, Router

    vocab = compiled.module.vocab_size
    requests = 3 * replicas
    factory = _engine_factory(compiled, max_slots, prompt_len, new_tokens,
                              requests + 4)
    rs = ReplicaSet(factory, initial=replicas, mount_ops=True)
    router = Router(rs)
    router.mount_ops(port=0)

    agg = FleetAggregator(dead_after=1.0, timeout=1.0)
    for rid, rep in rs.replicas.items():
        agg.add(f"http://127.0.0.1:{rep.engine.ops.port}", name=rid)
    agg.add(f"http://127.0.0.1:{router.ops.port}", name="router")
    poll_stop = threading.Event()

    def poller():
        while not poll_stop.is_set():
            agg.poll()
            poll_stop.wait(0.15)

    poll_thread = threading.Thread(target=poller, daemon=True)
    poll_thread.start()

    import numpy as np

    rng = np.random.default_rng(3)
    names = [f"s{i}" for i in range(2 * replicas)]
    # First turn pins every session somewhere (and warms the engines).
    for s in names:
        router.result(router.submit([1, 2, 3], max_new_tokens=2,
                                    session=s), timeout_s=60.0)
    victim = router.session_replica(names[0])

    # Long decodes in flight across the fleet, then kill the pinned
    # replica under them.
    rids = []
    for i in range(requests):
        plen = int(rng.integers(1, prompt_len + 1))
        prompt = rng.integers(1, vocab, plen).tolist()
        rids.append(router.submit(prompt, max_new_tokens=new_tokens,
                                  session=names[i % len(names)]))
    t_kill = time.perf_counter()
    rs.kill(victim)

    # Blackbox canary probes through the router while degraded.
    probes = []
    while time.perf_counter() - t_kill < 1.5:
        t_p = time.perf_counter()
        try:
            pid = router.submit([1, 2, 3], max_new_tokens=2, canary=True)
            ok = router.result(pid, timeout_s=5.0).status == "completed"
        except Exception:
            ok = False
        probes.append((t_p - t_kill, ok))
        time.sleep(0.05)
    fails = [t for t, ok in probes if not ok]
    outage_canary_s = (max(fails) - min(fails)) + 0.05 if fails else 0.0

    results = [router.result(r, timeout_s=120.0) for r in rids]
    misses_after_kill = router.affinity_misses

    # Restart the victim (same name, new boot, new port) and wait for
    # the fleet plane to narrate the full arc.
    while time.perf_counter() - t_kill < 2.0:
        time.sleep(0.05)
    rs.restart(victim)
    agg.add(f"http://127.0.0.1:{rs.get(victim).engine.ops.port}",
            name=victim)
    saw_outage = False
    t_recover = None
    deadline = time.perf_counter() + 20.0
    while time.perf_counter() < deadline:
        proc = agg.snapshot()["processes"].get(victim)
        if proc is not None:
            states = [s for _, s in proc["transitions"]]
            if "dead" in states and proc["status"] == "alive":
                saw_outage = True
                t_recover = time.perf_counter() - t_kill
                break
        time.sleep(0.1)
    poll_stop.set()
    poll_thread.join(timeout=5.0)

    slo = router.slo.snapshot()
    rec = {
        "mode": "fleet_kill",
        "replicas": replicas,
        "requests": requests,
        "victim": victim,
        "requeues": router.requeues,
        "affinity_misses_after_kill": misses_after_kill,
        "canary_probes": len(probes),
        "canary_failed_probes": len(fails),
        "outage_canary_s": outage_canary_s,
        "fleet_saw_replica_outage": saw_outage,
        "fleet_recover_s": t_recover,
        "goodput_ratio_after_kill": slo["goodput_ratio"],
        "all_completed": all(r.status == "completed" for r in results),
        "victim_boot_after": rs.get(victim).boot,
    }
    router.close()
    return rec


def bench_fleet_autoscale() -> dict:
    """Autoscaler replay arm: a seeded burn ladder (burst, then quiet)
    through the pure decision core. No engines, no clocks — the
    committed decision sequence IS the replay baseline; the gate's
    equal-rules hold the scale-up-under-burst and
    scale-down-after-cooldown bits."""
    from elephas_tpu.serving import FleetAutoscaler

    auto = FleetAutoscaler(min_replicas=1, max_replicas=3, up_burn=1.0,
                           down_burn=0.25, up_after=2, down_after=3,
                           cooldown_s=60.0)
    schedule = []
    t = 0.0
    for _ in range(4):          # seeded burst: sustained critical burn
        schedule.append((t, 5.0))
        t += 10.0
    for _ in range(12):         # quiet tail: budget recovered
        schedule.append((t, 0.0))
        t += 30.0
    n = 1
    for t_obs, burn in schedule:
        decision = auto.observe(burn=burn, n_replicas=n, now=t_obs)
        if decision == "up":
            n += 1
        elif decision == "down":
            n -= 1
    ups = [d["t"] for d in auto.decisions if d["direction"] == "up"]
    downs = [d["t"] for d in auto.decisions if d["direction"] == "down"]
    return {
        "mode": "fleet_autoscale",
        "observations": auto.observations,
        "decisions": [[d["t"], d["direction"], d["replicas"]]
                      for d in auto.decisions],
        "scaled_up_under_burst": bool(ups) and ups[0] <= 40.0,
        "scaled_down_after_cooldown": (bool(ups) and bool(downs)
                                       and downs[0] >= ups[0] + 60.0),
        "final_replicas": n,
    }


def bench_fleet_tenants(compiled, max_slots: int, prompt_len: int,
                        new_tokens: int, requests: int,
                        rounds: int = 3, attempts: int = 3) -> dict:
    """Tenancy guardrail + attribution proof (``--tenants``).

    Two claims in one arm. First, tagging is free: the standard
    workload with every submit carrying a ``tenant=`` tag must match
    the untagged arm token-for-token at < 2% throughput cost (same
    warmup/rounds/best-of discipline as the router overhead arm).
    Second, attribution is exact: a mixed two-tenant workload —
    ``interactive`` (short prompts, short decodes) interleaved with
    ``batch`` (full-length everything) — runs through the router with
    tracing live, and afterwards the per-tenant ledger must conserve
    tokens EXACTLY (sum over tenants of prefill/decode tokens ==
    the engine's ``ServingMetrics`` totals), and at least one
    ``serving_itl_seconds`` histogram exemplar must join a trace id
    present in the span dump (the p99-to-span-tree pivot the exemplar
    plane exists for)."""
    import tempfile
    import threading

    import numpy as np

    from elephas_tpu import obs
    from elephas_tpu.obs import Tracer
    from elephas_tpu.serving import InferenceEngine, ReplicaSet, Router

    vocab = compiled.module.vocab_size
    factory = _engine_factory(compiled, max_slots, prompt_len, new_tokens,
                              max(requests, 1) + 1)

    def run(tagged):
        engine = factory()
        stop = threading.Event()
        th = threading.Thread(target=engine.serve_forever, args=(stop,),
                              daemon=True)
        th.start()
        engine.result(engine.submit([1] * prompt_len, max_new_tokens=2),
                      timeout_s=60.0)
        seq = [0]

        def submit(p, n):
            if not tagged:
                return engine.submit(p, max_new_tokens=n)
            seq[0] += 1
            return engine.submit(
                p, max_new_tokens=n,
                tenant="interactive" if seq[0] % 2 else "batch")

        out = _fleet_workload(
            submit, lambda r: engine.result(r, timeout_s=120.0),
            vocab, prompt_len, new_tokens, requests)
        stop.set()
        th.join(timeout=10.0)
        return out

    run(True)  # warmup (compile + caches), discarded
    for attempt in range(attempts):
        plain, tagged = [], []
        for r in range(rounds):
            if r % 2 == 0:
                plain.append(run(False))
                tagged.append(run(True))
            else:
                tagged.append(run(True))
                plain.append(run(False))
        overhead = 1.0 - (max(x[0] for x in tagged)
                          / max(x[0] for x in plain))
        if overhead < 0.02:
            break
    token_identical = all(x[1] == plain[0][1] for x in plain + tagged)

    # -- attribution proof: mixed two-tenant traffic through the router,
    # tracing live so the finish-side exemplar latch has ids to latch.
    tracer = Tracer()

    def traced_factory():
        return InferenceEngine(
            compiled, max_slots=max_slots, max_prompt_len=prompt_len,
            max_len=prompt_len + new_tokens + 1,
            queue_depth=2 * requests + 4, pipeline=True, tracer=tracer)

    rs = ReplicaSet(traced_factory, initial=1)
    router = Router(rs)
    prompt_total = prompt_len  # router warmup bills as tenant "default"
    router.result(router.submit([1] * prompt_len, max_new_tokens=2),
                  timeout_s=60.0)
    rng = np.random.default_rng(11)
    rids = []
    by_tenant = {"interactive": [], "batch": []}
    for i in range(2 * requests):
        if i % 2 == 0:
            tenant = "interactive"
            plen = int(rng.integers(1, max(2, prompt_len // 2)))
            n = max(2, new_tokens // 4)
        else:
            tenant = "batch"
            plen = prompt_len
            n = new_tokens
        prompt = rng.integers(1, vocab, plen).tolist()
        prompt_total += plen
        rids.append((tenant,
                     router.submit(prompt, max_new_tokens=n,
                                   tenant=tenant)))
    results = []
    for tenant, rid in rids:
        res = router.result(rid, timeout_s=120.0)
        results.append(res)
        by_tenant[tenant].append(res)

    engine = next(iter(rs.replicas.values())).engine
    snap = engine.costs.snapshot()
    rows = snap["tenants"]
    dec_diff = (sum(r["decode_tokens"] for r in rows.values())
                - engine.metrics.tokens_out)
    pre_diff = (sum(r["prefill_tokens"] for r in rows.values())
                - prompt_total)

    # Exemplar→trace join: some ITL bucket's latched trace id must be a
    # trace id the span dump actually contains.
    reg_ex = obs.default_registry().exemplars().get(
        "serving_itl_seconds", {})
    exemplar_ids = {v for v in reg_ex.values() if v}
    with tempfile.TemporaryDirectory() as td:
        path = os.path.join(td, "trace.json")
        tracer.export_chrome(path)
        with open(path) as f:
            doc = json.load(f)
    trace_ids = {(e.get("args") or {}).get("trace_id")
                 for e in doc.get("traceEvents", ())}
    exemplar_joined = bool(exemplar_ids & trace_ids)

    def mean(xs):
        xs = [x for x in xs if x is not None]
        return sum(xs) / len(xs) if xs else None

    rec = {
        "mode": "fleet_tenants",
        "requests": 2 * requests,
        "rounds": rounds,
        "attempts_used": attempt + 1,
        "tokens_per_sec_plain": max(x[0] for x in plain),
        "tokens_per_sec_tagged": max(x[0] for x in tagged),
        "tenant_overhead_pct": overhead * 100.0,
        "token_identical": token_identical,
        "tenants": sorted(rows),
        "decode_tokens_by_tenant": {
            t: r["decode_tokens"] for t, r in sorted(rows.items())},
        "kv_block_seconds_by_tenant": {
            t: r["kv_block_seconds"] for t, r in sorted(rows.items())},
        "queue_seconds_by_tenant": {
            t: r["queue_seconds"] for t, r in sorted(rows.items())},
        "ttft_s_avg_by_tenant": {
            t: mean([r.ttft_s for r in rs_])
            for t, rs_ in sorted(by_tenant.items())},
        "itl_s_avg_by_tenant": {
            t: mean([r.itl_s_avg for r in rs_])
            for t, rs_ in sorted(by_tenant.items())},
        "tenant_token_conservation": float(abs(dec_diff) + abs(pre_diff)),
        "interactive_goodput_ratio": (
            rows["interactive"]["goodput"]["ratio"]),
        "batch_goodput_ratio": rows["batch"]["goodput"]["ratio"],
        "tenant_exemplar_joined": exemplar_joined,
        "all_completed": all(r.status == "completed" for r in results),
        "within_2pct": overhead < 0.02,
    }
    router.close()
    assert token_identical, "tagged token streams diverged from untagged"
    assert rec["tenant_token_conservation"] == 0.0, (
        f"attribution leak: decode diff {dec_diff}, prefill diff "
        f"{pre_diff} (per-tenant sums must equal fleet totals exactly)")
    assert rec["within_2pct"], (
        f"tenant tagging overhead {overhead * 100.0:.2f}% >= 2% after "
        f"{attempts} attempts")
    return rec


def bench_fleet_disagg(compiled, max_slots: int, prompt_len: int,
                       new_tokens: int, requests: int,
                       attempts: int = 3) -> dict:
    """Disaggregated-tiers arm (``--disagg``).

    The same two-tenant interference workload runs through two fleet
    topologies built from identical paged engines: a 2-replica
    monolithic fleet, and a 1-prefill + 1-decode tiered fleet where
    every request is prefilled on the prefill tier and its filled KV
    blocks cross the wire (``encode_handoff``/``submit_handoff``) to
    join the decode tier's batch. Four claims on one row:

    1. Identity — the tiered fleet serves byte-equal token streams to
       the monolithic fleet, request-for-request (the handoff is a
       transport, not a resample; gate equal-rule).
    2. Interference — decode-tier ITL p99 with the ``batch`` tenant
       streaming full-length prompts: on the monolithic fleet every
       batch prefill stalls a decode step, so the worst per-request
       mean inter-token gap eats whole prefill forwards; on the decode
       tier the only foreign work is the (device-side) block import.
       The committed ratio (decode tier's engine ITL p99 over the
       worst monolithic engine's) carries an absolute gate ceiling of
       1.0; retried ``attempts`` times for CI tail jitter. The
       interactive tenant's per-request view rides the row ungated —
       at CI scale its means are dominated by scheduler noise, while
       the engine-level p99 is where a stolen prefill step lands.
    3. Handoff cost — p50/p99 wall ms of export→encode→import,
       measured after a per-shape warmup (the import's donating
       scatter compiles once per block-count shape); p99 gate ceiling.
    4. QoS — both tenants run under admission (priority 0 vs 2,
       asymmetric weights) and the WORST tenant's goodput ratio is
       committed with an absolute floor: fair share may deprioritize
       the batch tenant, it must not starve it.

    Cross-tier prefix economics ride the same row: every prompt opens
    with a shared two-block system prefix, so after the first import
    the decode pool should satisfy each handoff's prefix from resident
    blocks — the committed hit rate is the fraction of handoffs that
    re-used at least one resident block (gate floor 0.5).
    """
    from concurrent.futures import ThreadPoolExecutor

    import numpy as np

    from elephas_tpu import obs
    from elephas_tpu.serving import InferenceEngine, ReplicaSet, Router
    from elephas_tpu.serving.fleet import QoSPolicy

    vocab = compiled.module.vocab_size
    block = max(2, prompt_len // 4)
    sys_prompt = np.random.default_rng(17).integers(
        1, vocab, 2 * block).tolist()
    # The interference must be REAL prefill work: batch prompts use the
    # model's whole sequence budget (the saturating-long-prompt shape
    # the --prefix ITL arm established), so on the monolithic fleet
    # every batch admission absorbs a full-length forward between two
    # decode steps. The decode tier's only foreign work is the block
    # import — a single donating scatter, whose cost does not grow
    # with prompt compute.
    interactive_new = min(new_tokens, 32)
    long_len = compiled.module.max_seq_len - interactive_new - 1

    def factory():
        return InferenceEngine(
            compiled,
            max_slots=max_slots,
            max_prompt_len=long_len,
            max_len=long_len + interactive_new + 1,
            queue_depth=2 * requests + 8,
            pipeline=True,
            paged=True,
            kv_block_size=block,
        )

    # One deterministic workload, shared by both arms: interactive
    # (short suffix, long decode — the ITL victim) interleaved with
    # batch (full-length prompts, short decodes — the interference).
    rng = np.random.default_rng(23)
    work = []
    for i in range(2 * requests):
        if i % 2 == 0:
            tenant = "interactive"
            plen = int(rng.integers(1, block + 1))
            n = interactive_new
        else:
            tenant = "batch"
            plen = long_len - len(sys_prompt)
            n = 2
        prompt = sys_prompt + rng.integers(1, vocab, plen).tolist()
        work.append((tenant, prompt, n))
    # Warmup shapes: one per distinct prompt block count (the decode
    # pool's import scatter compiles per shape; an unwarmed shape
    # would bill one XLA compile to a handoff sample).
    warm_prompts = [sys_prompt + [1] * 1, sys_prompt + [1] * (
        long_len - len(sys_prompt))]

    flight = obs.default_flight_recorder()

    def run(tiered):
        if tiered:
            rs = ReplicaSet(factory, tiers={"prefill": 1, "decode": 1})
            qos = QoSPolicy(
                buckets={"interactive": (1e9, 1e9), "batch": (1e9, 1e9)},
                weights={"interactive": 4.0, "batch": 1.0},
                priorities={"interactive": 0, "batch": 2})
            router = Router(rs, qos=qos)
        else:
            rs = ReplicaSet(factory, initial=2)
            router = Router(rs)
        for p in warm_prompts * 2:
            router.result(router.submit(p, max_new_tokens=2),
                          timeout_s=60.0)
        for rep in rs.replicas.values():
            rep.engine.metrics.reset()
        router._handoff_s.clear()  # timed samples only (warmup compiled)
        handoffs0, fails0 = router.handoffs, router.handoff_fails
        kv_evs0 = len(flight.events(kind="kv_handoff"))

        t0 = time.perf_counter()
        rids = [(tenant,
                 router.submit(prompt, max_new_tokens=n, tenant=tenant))
                for tenant, prompt, n in work]
        with ThreadPoolExecutor(max_workers=len(rids)) as ex:
            futs = [ex.submit(router.result, rid, 180.0)
                    for _, rid in rids]
            results = [f.result() for f in futs]
        dt = time.perf_counter() - t0

        streams = [list(r.tokens) for r in results]
        tps = sum(len(s) for s in streams) / dt
        itl_interactive = [
            r.itl_s_avg for (tenant, _, _), r in zip(work, results)
            if tenant == "interactive" and r.itl_s_avg is not None]
        if tiered:
            decode_eng = rs.serving("decode")[0].engine
            itl_engine_p99 = decode_eng.stats()["itl_s_p99"]
        else:
            itl_engine_p99 = max(
                rep.engine.stats()["itl_s_p99"]
                for rep in rs.replicas.values())
        # Per-tenant goodput: min ratio across engines (finish-side
        # ledgers live on whichever tier published the result).
        ratios = {}
        for rep in rs.replicas.values():
            for t, row in rep.engine.costs.snapshot()["tenants"].items():
                r = (row.get("goodput") or {}).get("ratio")
                if t in ("interactive", "batch") and r is not None:
                    ratios[t] = min(ratios.get(t, 1.0), r)
        kv_evs = flight.events(kind="kv_handoff")[kv_evs0:]
        out = {
            "tps": tps,
            "streams": streams,
            "ok": all(r.status == "completed" for r in results),
            "itl_interactive": itl_interactive,
            "itl_engine_p99": itl_engine_p99,
            "goodput_by_tenant": ratios,
            "handoffs": router.handoffs - handoffs0,
            "handoff_fails": router.handoff_fails - fails0,
            "handoff_s": list(router._handoff_s),
            "preemptions": router.preemptions,
            "prefix_matched": sum(
                1 for e in kv_evs if e.detail.get("matched", 0) >= 1),
        }
        router.close()
        return out

    def pctl(xs, q):
        xs = sorted(xs)
        return xs[min(len(xs) - 1, int(q * len(xs)))] if xs else None

    for attempt in range(attempts):
        mono = run(False)
        disagg = run(True)
        mono_p99 = pctl(mono["itl_interactive"], 0.99)
        dis_p99 = pctl(disagg["itl_interactive"], 0.99)
        ratio = (disagg["itl_engine_p99"] / mono["itl_engine_p99"]
                 if mono["itl_engine_p99"] else None)
        if ratio is not None and ratio <= 1.0:
            break
    token_identical = mono["streams"] == disagg["streams"]
    handoff_ms = [1000.0 * s for s in disagg["handoff_s"]]
    hit_rate = (disagg["prefix_matched"] / disagg["handoffs"]
                if disagg["handoffs"] else None)
    rec = {
        "mode": "fleet_disagg",
        "replicas_mono": 2,
        "tiers": {"prefill": 1, "decode": 1},
        "requests": 2 * requests,
        "kv_block_size": block,
        "sys_prompt_blocks": 2,
        "attempts_used": attempt + 1,
        "tokens_per_sec_mono": mono["tps"],
        "tokens_per_sec_disagg": disagg["tps"],
        "itl_s_p99_interactive_mono": mono_p99,
        "itl_s_p99_interactive_disagg": dis_p99,
        "itl_s_p99_engine_mono": mono["itl_engine_p99"],
        "itl_s_p99_engine_disagg": disagg["itl_engine_p99"],
        "disagg_itl_p99_ratio": ratio,
        "handoffs": disagg["handoffs"],
        "handoff_fails": disagg["handoff_fails"],
        "handoff_p50_ms": pctl(handoff_ms, 0.50),
        "handoff_p99_ms": pctl(handoff_ms, 0.99),
        "cross_tier_prefix_hit_rate": hit_rate,
        "goodput_by_tenant": disagg["goodput_by_tenant"],
        "goodput_floor_min_tenant": (
            min(disagg["goodput_by_tenant"].values())
            if disagg["goodput_by_tenant"] else None),
        "preemptions": disagg["preemptions"],
        "token_identical": token_identical,
        "all_completed": mono["ok"] and disagg["ok"],
    }
    assert token_identical, (
        "disaggregated token streams diverged from the monolithic fleet")
    assert disagg["handoff_fails"] == 0, (
        f"{disagg['handoff_fails']} handoffs degraded to local re-prefill")
    return rec


def bench_fleet_rollout(compiled, max_slots: int, prompt_len: int,
                        new_tokens: int, requests: int) -> dict:
    """Live-model-delivery arm (``--rollout``): a live trainer pushes
    into a PS group while the fleet serves, and the rollout plane
    delivers. Three phases on one row:

    1. **Swap tax + identity** — two 2-replica fleets run the standard
       seeded workload: one bare, one with a per-step version-gated
       ``WeightSubscriber`` (follow mode) on every engine while a
       trainer thread pushes ZERO deltas. The swaps are real (version
       changes, ``install_weights`` fires mid-stream) but the weights
       are byte-identical, so the token streams must equal the bare
       fleet's — the atomic-swap proof the gate holds with the
       ``token_identical`` equal-rule. The ITL p99 ratio between the
       arms is the swap tax (``swap_itl_p99_ratio``, ceiling 1.5), and
       a post-push quiet window measures the steady-state wire cost of
       the subscription (not-modified frames only).
    2. **Canary promote** — a 3-replica fleet under a
       ``RolloutController`` (goodput judge, short bake): one real
       delta push must reach every replica through the canary arc with
       zero dropped requests while traffic flows.
    3. **Forced rollback** — a second push with the judge pinned to
       "bad": the canary must return to the approved version, and
       ``rollback_served_stale`` counts non-canary replicas ever
       OBSERVED at the poisoned version — committed at exactly 0 (the
       blast-radius proof).

    The whole-arc ``rollout_goodput_ratio`` (router ledger, lifetime
    worst objective) carries the gate floor: delivery must not cost the
    fleet its attainment. The controller's replay-stable event digest
    rides the row for the incident-timeline cross-check.
    """
    import shutil
    import tempfile
    import threading

    import jax
    import numpy as np

    from elephas_tpu.parameter import ShardGroup
    from elephas_tpu.parameter.server import _ps_counters
    from elephas_tpu.rollout import (RolloutController, WeightSubscriber,
                                     goodput_judge)
    from elephas_tpu.serving import ReplicaSet, Router

    vocab = compiled.module.vocab_size
    factory = _engine_factory(compiled, max_slots, prompt_len, new_tokens,
                              2 * max(requests, 1) + 8)
    zero_delta = jax.tree_util.tree_map(
        lambda a: np.zeros_like(np.asarray(a)), compiled.params)
    _, bytes_tx, _ = _ps_counters("socket")

    # -- phase 1: swap tax + mid-stream token identity ------------------
    def run_arm(subscribe: bool):
        group = ShardGroup(compiled.params, 2, mode="socket")
        group.start()
        rs = ReplicaSet(factory, initial=2)
        router = Router(rs)
        stop = threading.Event()
        pusher = None
        subs = []
        try:
            router.result(router.submit([1] * prompt_len, max_new_tokens=2),
                          timeout_s=60.0)
            for rep in rs.serving():
                rep.engine.metrics.reset()
            if subscribe:
                client = group.client()
                subs = [WeightSubscriber(client, every=1, follow=True)
                        .attach(rep.engine) for rep in rs.serving()]

                def push_loop():
                    trainer = group.client()
                    while not stop.is_set():
                        trainer.update_parameters(zero_delta)
                        time.sleep(0.03)

                pusher = threading.Thread(target=push_loop, daemon=True)
                pusher.start()
            tps, tokens, results = _fleet_workload(
                lambda p, n: router.submit(p, max_new_tokens=n),
                lambda r: router.result(r, timeout_s=120.0),
                vocab, prompt_len, new_tokens, requests)
            stop.set()
            if pusher is not None:
                pusher.join(timeout=5.0)
            steady = None
            if subscribe:
                # Quiet window: pushes stopped, version static — every
                # subscriber poll must now cost only not-modified
                # frames. The byte delta is the steady-state wire tax.
                polls0 = sum(s.pulls for s in subs)
                b0 = bytes_tx.value
                _fleet_workload(
                    lambda p, n: router.submit(p, max_new_tokens=n),
                    lambda r: router.result(r, timeout_s=120.0),
                    vocab, prompt_len, new_tokens, max(4, requests // 3))
                polls = sum(s.pulls for s in subs) - polls0
                steady = {
                    "bytes": bytes_tx.value - b0,
                    "polls": polls,
                    "swaps": sum(s.swaps for s in subs),
                    "unchanged": sum(s.unchanged for s in subs),
                    "failures": sum(s.failures for s in subs),
                }
            itl = max(rep.engine.stats()["itl_s_p99"] or 0.0
                      for rep in rs.serving())
            ok = all(r.status == "completed" for r in results)
            return tokens, itl, ok, steady
        finally:
            router.close()
            group.stop()

    bare_tokens, bare_itl, bare_ok, _ = run_arm(False)
    swap_tokens, swap_itl, swap_ok, steady = run_arm(True)
    token_identical = bare_tokens == swap_tokens
    swap_ratio = (swap_itl / bare_itl) if bare_itl else None
    assert token_identical, (
        "mid-stream zero-delta swaps changed the token streams — the "
        "step-boundary install is not atomic")
    assert steady["swaps"] >= 1, (
        "the subscriber arm never actually swapped — the phase proved "
        "nothing")

    # -- phases 2+3: canary promote, then forced rollback ---------------
    wal_root = tempfile.mkdtemp(prefix="rollout-bench-wal-")
    group = ShardGroup(compiled.params, 2, mode="socket",
                       wal_root=wal_root, wal_keep=16)
    group.start()
    rs = ReplicaSet(factory, initial=3)
    router = Router(rs)
    ctrl = RolloutController(
        rs, group.client(), bake_s=0.2, min_results=2,
        judge=goodput_judge(tolerance=0.5))
    router.attach_rollout(ctrl)
    trainer = group.client()
    real_delta = jax.tree_util.tree_map(
        lambda a: np.full_like(np.asarray(a), 1e-4), compiled.params)
    rng = np.random.default_rng(31)
    all_ok = [True]
    stale = [0]
    bad_version = [None]

    def wave(n: int):
        rids = []
        for _ in range(n):
            plen = int(rng.integers(1, prompt_len + 1))
            prompt = rng.integers(1, vocab, plen).tolist()
            rids.append(router.submit(prompt, max_new_tokens=new_tokens))
        for r in rids:
            res = router.result(r, timeout_s=120.0)
            all_ok[0] = all_ok[0] and res.status == "completed"
            router.tick()
            if bad_version[0] is not None:
                for rep in rs.serving():
                    if rep.rollout_canary or rep.engine is None:
                        continue
                    if rep.engine.model_version == bad_version[0]:
                        stale[0] += 1

    try:
        router.result(router.submit([1] * prompt_len, max_new_tokens=2),
                      timeout_s=60.0)
        router.tick()  # seeds the approved baseline (version 0)
        base = ctrl.doc()["approved_version"]
        trainer.update_parameters(real_delta)
        good_version = (base or 0) + 1
        deadline = time.perf_counter() + 90.0
        while ctrl.rollouts < 1 and time.perf_counter() < deadline:
            wave(3)
        promoted = ctrl.rollouts >= 1
        converged = promoted and all(
            rep.engine.model_version == good_version
            for rep in rs.serving())
        assert converged, (
            f"promote arc did not converge: phase={ctrl.doc()['phase']} "
            f"versions={ctrl.doc()['versions']}")

        ctrl.judge = lambda canary, fleet, window_s, now: False
        trainer.update_parameters(real_delta)
        bad_version[0] = good_version + 1
        deadline = time.perf_counter() + 90.0
        while ctrl.rollbacks < 1 and time.perf_counter() < deadline:
            wave(3)
        doc = ctrl.doc()
        rolled_back = ctrl.rollbacks >= 1
        assert rolled_back and doc["approved_version"] == good_version, (
            f"rollback arc did not converge: phase={doc['phase']} "
            f"approved={doc['approved_version']}")
        slo = router.slo.snapshot()
        rec = {
            "mode": "fleet_rollout",
            "replicas": 3,
            "requests": requests,
            "token_identical": token_identical,
            "all_completed": bare_ok and swap_ok and all_ok[0],
            "swap_itl_p99_ratio": swap_ratio,
            "itl_s_p99_bare": bare_itl,
            "itl_s_p99_subscribed": swap_itl,
            "steady_pull_bytes": steady["bytes"],
            "steady_pull_polls": steady["polls"],
            "steady_pull_bytes_per_poll": (
                steady["bytes"] / steady["polls"] if steady["polls"]
                else None),
            "swaps_delivered": steady["swaps"],
            "pull_failures": steady["failures"],
            "rollout_promoted": ctrl.rollouts,
            "rollout_rolled_back": ctrl.rollbacks,
            "rollback_served_stale": stale[0],
            "rollout_goodput_ratio": slo["goodput_ratio"],
            "approved_version": doc["approved_version"],
            "rejected_version": bad_version[0],
            "rollout_digest": doc["digest"],
            "rollout_events": [e["kind"] for e in doc["events"]],
        }
    finally:
        router.close()
        group.stop()
        shutil.rmtree(wal_root, ignore_errors=True)
    assert stale[0] == 0, (
        f"{stale[0]} non-canary observations served the poisoned "
        "version — canary containment failed")
    return rec


def main(argv=None) -> list:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--batches", type=int, nargs="+", default=[1, 8, 32])
    parser.add_argument("--prompt-len", type=int, default=32)
    parser.add_argument("--new", type=int, default=64)
    parser.add_argument("--reps", type=int, default=3)
    parser.add_argument("--vocab", type=int, default=512)
    parser.add_argument("--d-model", type=int, default=128)
    parser.add_argument("--heads", type=int, default=8)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--serving-slots", type=int, default=4)
    parser.add_argument("--serving-requests", type=int, default=12)
    parser.add_argument("--out", type=str, default=None,
                        help="also write records as a JSON array")
    parser.add_argument("--serve-out", type=str, default=None,
                        help="write the serving arms (before/after "
                             "pipelining) as their own JSON artifact")
    parser.add_argument("--trace", type=str, default=None,
                        help="record one traced pipelined serving run's "
                             "span tree to this Chrome trace JSON, plus a "
                             "trace_report.py summary next to it (.md)")
    parser.add_argument("--no-overhead-check", action="store_true",
                        help="skip the traced-vs-untraced < 2%% guardrail "
                             "(6 extra serving runs)")
    parser.add_argument("--store-overhead", action="store_true",
                        help="append the durable-telemetry-store "
                             "overhead row: serving throughput with the "
                             "ops endpoint mounted, store vs no store "
                             "(gated under 2%% like trace/canary)")
    parser.add_argument("--slo", action="store_true",
                        help="run the goodput + blackbox-canary arm "
                             "(SLO attainment ratios, canary probe SLIs, "
                             "and the canaried-vs-plain < 2%% overhead "
                             "measurement)")
    parser.add_argument("--prefix", action="store_true",
                        help="run the paged-pool arm: prefix-cache hit "
                             "economics on a shared-system-prompt "
                             "multi-turn workload, paged-vs-contiguous "
                             "token identity, and the chunked-vs-"
                             "unchunked prefill ITL p99 tail")
    parser.add_argument("--spec", action="store_true",
                        help="run the speculative-decoding arm: draft-"
                             "and-verify vs the unspeculated oracle on "
                             "the shared-prefix workload — accept rate, "
                             "tokens/step, ITL ratio, token identity, "
                             "compile-counter pins; draft params "
                             "delivered by a real 2-shard PS group")
    parser.add_argument("--gamma", type=int, default=3,
                        help="draft window length for the --spec arm")
    parser.add_argument("--fleet", action="store_true",
                        help="run the replicated-fleet arms: routed-vs-"
                             "bare overhead + token identity, N-replica "
                             "session-affinity throughput, kill-a-"
                             "replica-mid-traffic chaos, and the "
                             "autoscaler decision replay")
    parser.add_argument("--tenants", action="store_true",
                        help="run the two-tenant cost-attribution arm: "
                             "tagged-vs-untagged overhead (< 2%%), mixed "
                             "interactive/batch traffic through the "
                             "router with exact per-tenant token "
                             "conservation and the exemplar-to-trace "
                             "join (appends to the fleet artifact)")
    parser.add_argument("--disagg", action="store_true",
                        help="run the disaggregated prefill/decode tier "
                             "arm: tiered-vs-monolithic token identity "
                             "across the KV-block handoff, decode-tier "
                             "ITL p99 under long-prompt interference, "
                             "handoff latency p50/p99, cross-tier "
                             "prefix hits, and the per-tenant fair-"
                             "share goodput floor (appends to the "
                             "fleet artifact)")
    parser.add_argument("--rollout", action="store_true",
                        help="run the live-model-delivery arm: "
                             "mid-stream swap identity + swap-tax ITL "
                             "ratio, steady-state subscription bytes, "
                             "and a full canary promote + forced "
                             "rollback under a live trainer (appends "
                             "to the fleet artifact)")
    parser.add_argument("--fleet-out", type=str, default=None,
                        help="write the fleet arms as their own JSON "
                             "artifact (BENCH_FLEET.json)")
    parser.add_argument("--fleet-replicas", type=int, default=3)
    parser.add_argument("--fleet-sessions", type=int, default=6)
    parser.add_argument("--fleet-turns", type=int, default=4)
    args = parser.parse_args(argv)

    import jax

    compiled = build_model(
        args.vocab, args.d_model, args.heads, args.layers,
        max_seq=args.prompt_len + args.new + 1,
    )
    records = [{
        "backend": jax.default_backend(),
        "device_kind": jax.devices()[0].device_kind,
        "params": compiled.count_params(),
        "d_model": args.d_model,
        "layers": args.layers,
    }]
    for batch in args.batches:
        for use_cache in (True, False):
            rec = bench_generate(
                compiled, batch, args.prompt_len, args.new, use_cache,
                args.reps,
            )
            records.append(rec)
            print(json.dumps(rec))
    serving_records = []
    for pipeline in (False, True):  # reference first, then the hot path
        rec = bench_serving(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests, pipeline=pipeline,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if not args.no_overhead_check:
        rec = bench_trace_overhead(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.store_overhead:
        rec = bench_store_overhead(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.slo:
        # 3x the serving-arm request count: the canary arm measures
        # probe cost as a throughput delta, and at the base count the
        # fixed 3 probes are a 25% probe rate — an interference stress
        # test, not the guardrail's claim. Tripling the real traffic
        # amortizes probes to ~8%, still far above any production
        # canary rate, so the 2% ceiling gates probe COST rather than
        # the workload's granularity.
        rec = bench_slo(
            compiled, args.serving_slots, args.prompt_len, args.new,
            3 * args.serving_requests,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.prefix:
        rec = bench_prefix(
            compiled, args.serving_slots, args.prompt_len, args.new,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.spec:
        rec = bench_spec(
            compiled, args.serving_slots, args.prompt_len, args.new,
            gamma=args.gamma,
        )
        serving_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    fleet_records = []
    if args.fleet:
        for rec in (
            bench_fleet_routed_vs_bare(
                compiled, args.serving_slots, args.prompt_len, args.new,
                args.serving_requests,
            ),
            bench_fleet_n(
                compiled, args.serving_slots, args.prompt_len, args.new,
                replicas=args.fleet_replicas,
                sessions=args.fleet_sessions, turns=args.fleet_turns,
            ),
            bench_fleet_kill(
                compiled, args.serving_slots, args.prompt_len, args.new,
                replicas=args.fleet_replicas,
            ),
            bench_fleet_autoscale(),
        ):
            fleet_records.append(rec)
            records.append(rec)
            print(json.dumps(rec))
    if args.tenants:
        rec = bench_fleet_tenants(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        fleet_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.disagg:
        rec = bench_fleet_disagg(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        fleet_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.rollout:
        rec = bench_fleet_rollout(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests,
        )
        fleet_records.append(rec)
        records.append(rec)
        print(json.dumps(rec))
    if args.trace:
        from elephas_tpu.obs import Tracer

        import scripts.trace_report as trace_report

        tracer = Tracer()
        bench_serving(
            compiled, args.serving_slots, args.prompt_len, args.new,
            args.serving_requests, pipeline=True, tracer=tracer,
        )
        tracer.export_chrome(args.trace)
        report_path = os.path.splitext(args.trace)[0] + ".md"
        text = trace_report.report(args.trace)
        with open(report_path, "w") as f:
            f.write(text)
        print(f"trace: {args.trace} (Perfetto-viewable); report: "
              f"{report_path}")
    if args.out:
        with open(args.out, "w") as f:
            json.dump(records, f, indent=1)
    if args.serve_out:
        with open(args.serve_out, "w") as f:
            json.dump([records[0], *serving_records], f, indent=1)
    if args.fleet_out:
        with open(args.fleet_out, "w") as f:
            json.dump([records[0], *fleet_records], f, indent=1)
    return records


if __name__ == "__main__":
    main()
