#!/usr/bin/env python
"""Compatibility shim over ``elephas_tpu.analysis.legacy``.

The eight lint domains that grew here (host-sync, serving-clock,
ps-pickle, resilience-clock, metric-naming, kind-vocab, route-vocab,
pool-boundary) now live in the analysis subsystem, where they share the
AST walker, pragma machinery, and rule registry with the concurrency
analyzers — run ``python -m elephas_tpu.analysis`` for the full driver
(``--list-rules`` for the inventory). This module re-exports the
historical functional API unchanged so existing imports and the tier-1
suite (``tests/test_lint_blocking.py``) keep working; running it as a
script behaves exactly as before.
"""

from elephas_tpu.analysis.legacy import (  # noqa: F401
    CLOCK_PRAGMA,
    KIND_PRAGMA,
    METRIC_PRAGMA,
    PICKLE_PRAGMA,
    PICKLE_SANCTIONED,
    POOL_PRAGMA,
    POOL_SANCTIONED,
    PRAGMA,
    ROUTE_PRAGMA,
    SANCTIONED,
    Violation,
    lint_file,
    lint_kind_file,
    lint_kind_package,
    lint_metric_file,
    lint_metric_package,
    lint_package,
    lint_pickle_file,
    lint_pickle_package,
    lint_pool_file,
    lint_pool_package,
    lint_resilience_file,
    lint_resilience_package,
    lint_route_file,
    lint_route_package,
    load_registered_vocab,
    load_route_vocab,
    main,
)

if __name__ == "__main__":
    import sys

    sys.exit(1 if main() else 0)
