#!/usr/bin/env python
"""Static check: no blocking device→host syncs in the serving hot path.

The pipelined scheduler's whole value is that the device never waits on
Python — decode step N+1 is dispatched before step N's tokens are read,
and the ONLY place a device value may cross to the host is
``elephas_tpu/serving/host_sync.py``. A single stray ``int(device_val)``
anywhere else silently serializes every step and erases the overlap, so
this lint walks the serving package's ASTs and rejects every
host-conversion call outside the sanctioned module:

- ``int(...)`` / ``float(...)``        (implicit blocking scalar fetch)
- ``.item()`` / ``.tolist()``          (explicit blocking conversions)
- ``np.asarray(...)`` / ``np.array(...)`` (numpy coercion of a possibly
  device array — host upload belongs to ``jnp.asarray``)
- ``jax.device_get(...)``              (the raw transfer primitive)
- ``.block_until_ready()`` / ``jax.block_until_ready(...)``

A second rule guards the serving package's CLOCK DOMAIN: scheduler,
metrics, and tracer all take an injectable ``clock=`` (tests drive them
with fakes; spans are recorded retroactively with scheduler timestamps),
so a raw ``time.time()`` / ``time.perf_counter()`` /
``time.monotonic()`` call in serving code silently mixes wall domains —
timestamps stop comparing against the injected clock's. Such calls are
flagged; read the time through ``self.clock()`` instead. (Bare
``time.monotonic`` as a default-argument VALUE is fine — only calls are
flagged.)

Escape hatch: a line whose source carries a ``# host-ok`` pragma is
exempt — for conversions of values that PROVABLY never touched the
device (caller-supplied python ints, numpy buffers already fetched
through ``host_sync``), or host-only timing genuinely outside the
scheduled path. The pragma keeps every exemption greppable.

A third rule guards the PARAMETER-SERVER WIRE PATH: the packed codec
(``elephas_tpu/parameter/wire.py``) replaced per-request pickling on
the PS hot path, and ``wire.encode_pickle``/``wire.decode_pickle`` are
the only sanctioned legacy-interop entry points. A direct
``pickle.dumps(...)`` / ``pickle.loads(...)`` (or ``dump``/``load``)
anywhere else in ``elephas_tpu/parameter/`` silently reintroduces the
full-copy serialization the codec exists to remove — and worse, a
``loads`` added before the HMAC check would reopen the
verify-before-decode hole. Flagged outside ``wire.py``; the escape
pragma is ``# pickle-ok``.

A fourth rule guards the RESILIENCE CLOCK DOMAIN
(``elephas_tpu/resilience/``): failure detection, MTTR measurement, and
fault injection are all specified against injectable ``clock=`` /
``sleep=`` hooks so chaos tests replay deterministically on fake time
with zero real waiting. A raw ``time.time()`` / ``time.monotonic()`` /
``time.perf_counter()`` — or, new in this domain, a raw ``time.sleep()``
— hard-wires wall time into a code path tests need to drive, so all four
are flagged anywhere in the resilience package. ``time.monotonic`` /
``time.sleep`` as default-argument VALUES are fine (that IS the
injection pattern); only calls are flagged. Escape pragma:
``# clock-ok``, for timing provably outside any detector/injector path.

A fifth rule enforces METRIC NAMING across the whole package: the
registry grew Prometheus label support, so dimensions belong in
``labelnames=``, never baked into the metric name — and Prometheus
conventions make the unit part of the name. Any ``.counter("name")``
call whose literal name doesn't end in ``_total``, any
``.histogram("name")`` not ending in ``_seconds``, and any f-string
name on either (an f-string IS a baked dimension — ``retrace_total::
{program}`` was exactly the shape the label migration removed) is
flagged. Names that arrive through a variable are not judged — the
literal lives at its definition site, which is linted there. Gauges
are unconstrained (no unit convention fits them all). Escape pragma:
``# metric-ok``, for deliberate deviations (e.g. a bridge exporting a
foreign system's names verbatim).

A sixth rule closes the ANOMALY/ALERT VOCABULARY: FlightRecorder event
kinds and SLO alert rule names are what dashboards, runbooks, and the
alert engine's rule pack key on, so both come from registered-constant
tables — ``obs.flight.KINDS`` and ``obs.alerts.RULE_NAMES``. A string
literal passed positionally to ``.note("…")`` (the span ``note`` takes
kwargs only, so a positional string is uniquely the flight recorder's)
or as ``AlertRule("…")``'s name / ``kind=`` that isn't in its table is
flagged, as is any f-string there. The vocabularies are read from the
defining modules' ASTs — the lint never imports the package. Grow the
table to add a kind; ``# kind-ok`` escapes deliberate test-local vocab.
This rule also scans ``scripts/``.

A seventh rule closes the OPS ROUTE VOCABULARY: every path the
``OpsServer`` serves is registered through ``add_route("/…")`` against
the ``obs.opsd.ROUTES`` constant — the table ``/meta`` advertises, 404
bodies list, and the fleet aggregator polls. A route string at an
``add_route``/``_add_route`` call site that isn't in ``ROUTES`` (or any
f-string path) means the served surface and the documented surface have
drifted, so it's flagged; grow ``ROUTES`` to add a route. The
vocabulary is AST-read from ``opsd.py`` like the kind tables. Escape
pragma: ``# route-ok``, for test-local throwaway routes. This rule also
scans ``scripts/``.

An eighth rule guards the paged pool's DONATION BOUNDARY: the
``PagedKVPool`` cache pytree is donated to every compiled program that
rewrites it (chunk prefill, paged decode, copy-on-write block copies),
and the ONLY safe access path is the pool's guarded ``cache`` property
plus ``swap()`` to reinstall — both live in ``serving/kv_pool.py``. An
attribute read of ``._cache`` / ``._pad`` anywhere else in the serving
package reaches past the ``DonatedBufferError`` guard and can hand out
deleted buffers that surface as opaque XLA errors far from the bug.
Flagged outside ``kv_pool.py``; escape pragma ``# pool-ok``, for code
that provably holds a never-donated tree.

Wired into tier-1 via ``tests/test_lint_blocking.py``; also runnable
standalone: ``python scripts/lint_blocking.py`` (exit 1 on violations).
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List, NamedTuple, Tuple

PRAGMA = "host-ok"
SANCTIONED = "host_sync.py"
PICKLE_PRAGMA = "pickle-ok"
PICKLE_SANCTIONED = "wire.py"
CLOCK_PRAGMA = "clock-ok"
METRIC_PRAGMA = "metric-ok"
KIND_PRAGMA = "kind-ok"
ROUTE_PRAGMA = "route-ok"
POOL_PRAGMA = "pool-ok"
POOL_SANCTIONED = "kv_pool.py"
_POOL_PRIVATE = ("_cache", "_pad")
_NUMPY_NAMES = ("np", "numpy")
_CLOCK_ATTRS = ("time", "perf_counter", "monotonic")
_PICKLE_ATTRS = ("dumps", "loads", "dump", "load")
_METRIC_SUFFIX = {"counter": "_total", "histogram": "_seconds"}


class Violation(NamedTuple):
    path: str
    lineno: int
    call: str
    line: str
    domain: str = "serving"

    def __str__(self):
        if self.domain == "route":
            return (
                f"{self.path}:{self.lineno}: unregistered route "
                f"{self.call} — opsd routes come from obs.opsd.ROUTES "
                f"(grow the table so /meta, 404 bodies, and the fleet "
                f"poller stay in sync; `# {ROUTE_PRAGMA}` for test-local "
                f"throwaway routes)\n    {self.line.strip()}"
            )
        if self.domain == "kind":
            return (
                f"{self.path}:{self.lineno}: unregistered {self.call} — "
                f"FlightRecorder kinds come from obs.flight.KINDS and "
                f"alert rule names from obs.alerts.RULE_NAMES (grow the "
                f"table, never invent the string inline; `# {KIND_PRAGMA}` "
                f"for deliberate local vocab)\n    {self.line.strip()}"
            )
        if self.domain == "metric":
            return (
                f"{self.path}:{self.lineno}: metric name {self.call} "
                f"violates naming (counters end `_total`, histograms end "
                f"`_seconds`; an f-string name bakes a dimension into it — "
                f"use labelnames=; `# {METRIC_PRAGMA}` for deliberate "
                f"foreign names)\n    {self.line.strip()}"
            )
        if self.domain == "pool":
            return (
                f"{self.path}:{self.lineno}: donated-pool internal "
                f"{self.call} read outside kv_pool.py — donated buffers "
                f"must go through the guarded `pool.cache`/`pool.pad` "
                f"properties and `pool.swap()` (a raw `._cache` read can "
                f"hand out deleted buffers; `# {POOL_PRAGMA}` only for a "
                f"tree provably never donated)\n    {self.line.strip()}"
            )
        if self.domain == "resilience":
            what = "raw sleep" if self.call == "time.sleep" \
                else "raw clock call"
            return (
                f"{self.path}:{self.lineno}: {what} `{self.call}` in "
                f"resilience code bypasses the injected clock/sleep hooks "
                f"(thread a `clock=`/`sleep=` parameter so chaos tests run "
                f"on fake time; `# {CLOCK_PRAGMA}` only for timing outside "
                f"every detector/injector path)\n    {self.line.strip()}"
            )
        if self.call.startswith("pickle."):
            return (
                f"{self.path}:{self.lineno}: direct `{self.call}` outside "
                f"wire.py reintroduces per-request pickling on the PS hot "
                f"path (route through wire.encode_pickle/decode_pickle; "
                f"`# {PICKLE_PRAGMA}` only for data that never crosses the "
                f"wire)\n    {self.line.strip()}"
            )
        if self.call.startswith("time."):
            return (
                f"{self.path}:{self.lineno}: raw clock call `{self.call}` "
                f"bypasses the injected serving clock (read `self.clock()`; "
                f"`# {PRAGMA}` only for timing outside the scheduled path)"
                f"\n    {self.line.strip()}"
            )
        return (
            f"{self.path}:{self.lineno}: blocking host sync `{self.call}` "
            f"outside host_sync.py (add `# {PRAGMA}` only if the value "
            f"never touched the device)\n    {self.line.strip()}"
        )


def _call_name(node: ast.Call) -> str | None:
    """The lint-relevant name of a call, or None if it's not watched."""
    fn = node.func
    if isinstance(fn, ast.Name) and fn.id in ("int", "float"):
        return fn.id
    if isinstance(fn, ast.Attribute):
        if fn.attr in ("item", "tolist", "block_until_ready", "device_get"):
            return f".{fn.attr}" if fn.attr != "device_get" else "device_get"
        if fn.attr in ("asarray", "array") and isinstance(fn.value, ast.Name) \
                and fn.value.id in _NUMPY_NAMES:
            return f"{fn.value.id}.{fn.attr}"
        if fn.attr in _CLOCK_ATTRS and isinstance(fn.value, ast.Name) \
                and fn.value.id == "time":
            return f"time.{fn.attr}"
    return None


def lint_file(path: Path) -> List[Violation]:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _call_name(node)
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if PRAGMA in line:
            continue
        out.append(Violation(str(path), node.lineno, name, line))
    return out


def lint_package(root: Path) -> List[Violation]:
    """Lint every module in the serving package — recursively, so
    subpackages (``serving/fleet/``) inherit the blocking-read and
    clock-call bans — except the sanctioned sync point itself."""
    out = []
    for path in sorted(root.rglob("*.py")):
        if path.name == SANCTIONED:
            continue
        out.extend(lint_file(path))
    return out


def _pickle_call_name(node: ast.Call) -> str | None:
    """``pickle.dumps``-style attribute calls; bare ``loads(...)`` from a
    ``from pickle import loads`` is caught too (module-qualified name is
    synthesized so the message stays uniform)."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and fn.attr in _PICKLE_ATTRS \
            and isinstance(fn.value, ast.Name) \
            and fn.value.id in ("pickle", "cPickle"):
        return f"pickle.{fn.attr}"
    return None


def lint_pickle_file(path: Path) -> List[Violation]:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    imported = set()  # names bound by `from pickle import dumps as d`
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "pickle":
            for alias in node.names:
                if alias.name in _PICKLE_ATTRS:
                    imported.add(alias.asname or alias.name)
        if not isinstance(node, ast.Call):
            continue
        name = _pickle_call_name(node)
        if name is None and isinstance(node.func, ast.Name) \
                and node.func.id in imported:
            name = f"pickle.{node.func.id}"
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if PICKLE_PRAGMA in line:
            continue
        out.append(Violation(str(path), node.lineno, name, line))
    return out


def lint_pickle_package(root: Path) -> List[Violation]:
    """Lint every module in the parameter package except the sanctioned
    codec home itself."""
    out = []
    for path in sorted(root.glob("*.py")):
        if path.name == PICKLE_SANCTIONED:
            continue
        out.extend(lint_pickle_file(path))
    return out


def _resilience_call_name(node: ast.Call) -> str | None:
    """``time.<clock>()`` AND ``time.sleep()`` — the resilience domain
    bans both (everything there takes ``clock=``/``sleep=`` hooks)."""
    fn = node.func
    if isinstance(fn, ast.Attribute) and isinstance(fn.value, ast.Name) \
            and fn.value.id == "time" \
            and fn.attr in _CLOCK_ATTRS + ("sleep",):
        return f"time.{fn.attr}"
    return None


def lint_resilience_file(path: Path) -> List[Violation]:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _resilience_call_name(node)
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if CLOCK_PRAGMA in line:
            continue
        out.append(Violation(str(path), node.lineno, name, line,
                             domain="resilience"))
    return out


def lint_resilience_package(root: Path) -> List[Violation]:
    """Lint every module in the resilience package — no sanctioned file:
    real wall time enters ONLY through default-argument values."""
    out = []
    for path in sorted(root.glob("*.py")):
        out.extend(lint_resilience_file(path))
    return out


def _metric_call_name(node: ast.Call) -> str | None:
    """``<anything>.counter("…")`` / ``.histogram("…")`` with a judgeable
    first argument: a string literal that breaks the suffix convention,
    or any f-string (a baked dimension). Variable names pass — their
    literal is linted where it's defined."""
    fn = node.func
    if not (isinstance(fn, ast.Attribute) and fn.attr in _METRIC_SUFFIX
            and node.args):
        return None
    arg = node.args[0]
    if isinstance(arg, ast.JoinedStr):
        return f"<f-string> in .{fn.attr}()"
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and not arg.value.endswith(_METRIC_SUFFIX[fn.attr]):
        return f"`{arg.value}` in .{fn.attr}()"
    return None


def lint_metric_file(path: Path) -> List[Violation]:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = _metric_call_name(node)
        if name is None:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if METRIC_PRAGMA in line:
            continue
        out.append(Violation(str(path), node.lineno, name, line,
                             domain="metric"))
    return out


def lint_metric_package(root: Path) -> List[Violation]:
    """Lint EVERY module of the package tree — metric names are a
    process-global namespace, so no file is exempt."""
    out = []
    for path in sorted(root.rglob("*.py")):
        out.extend(lint_metric_file(path))
    return out


def load_registered_vocab(pkg_root: Path):
    """``(KINDS, RULE_NAMES)`` read straight from the defining modules'
    ASTs — pure-literal tuples by construction, so ``literal_eval``
    suffices and the lint never has to import the package (which would
    drag in jax)."""
    out = {}
    for fname, const in (("flight.py", "KINDS"), ("alerts.py", "RULE_NAMES")):
        tree = ast.parse((pkg_root / "obs" / fname).read_text())
        for node in tree.body:
            if isinstance(node, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == const
                    for t in node.targets):
                out[const] = tuple(ast.literal_eval(node.value))
    return out["KINDS"], out["RULE_NAMES"]


def _kind_call_names(node: ast.Call, kinds, rule_names) -> List[str]:
    """Unregistered-vocabulary findings for one call. A positional
    string to ``.note(…)`` is uniquely a FlightRecorder kind (span
    ``note`` is kwargs-only); ``AlertRule(…)`` is judged on its name
    (first positional) and ``kind=`` keyword. Strings that arrive
    through variables pass — the literal is linted at its definition."""
    fn = node.func
    found = []

    def judge(arg, vocab, where):
        if isinstance(arg, ast.JoinedStr):
            found.append(f"<f-string> {where}")
        elif isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                and arg.value not in vocab:
            found.append(f"`{arg.value}` {where}")

    if isinstance(fn, ast.Attribute) and fn.attr == "note" and node.args:
        judge(node.args[0], kinds, "kind in .note()")
    callee = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if callee == "AlertRule":
        if node.args:
            judge(node.args[0], rule_names, "rule name in AlertRule()")
        for kw in node.keywords:
            if kw.arg == "kind":
                judge(kw.value, kinds, "kind in AlertRule()")
    return found


def lint_kind_file(path: Path, kinds, rule_names) -> List[Violation]:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        names = _kind_call_names(node, kinds, rule_names)
        if not names:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if KIND_PRAGMA in line:
            continue
        for name in names:
            out.append(Violation(str(path), node.lineno, name, line,
                                 domain="kind"))
    return out


def lint_kind_package(pkg_root: Path,
                      extra_roots: Tuple[Path, ...] = ()) -> List[Violation]:
    """Lint the whole package tree plus any extra roots (``scripts/``) —
    the vocabulary is process-global, so no file is exempt."""
    kinds, rule_names = load_registered_vocab(pkg_root)
    out = []
    paths = sorted(pkg_root.rglob("*.py"))
    for root in extra_roots:
        paths.extend(sorted(root.glob("*.py")))
    for path in paths:
        out.extend(lint_kind_file(path, kinds, rule_names))
    return out


def load_route_vocab(pkg_root: Path) -> Tuple[str, ...]:
    """``ROUTES`` read straight from ``obs/opsd.py``'s AST — a
    pure-literal tuple by construction, so ``literal_eval`` suffices and
    the lint never imports the package."""
    tree = ast.parse((pkg_root / "obs" / "opsd.py").read_text())
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
                isinstance(t, ast.Name) and t.id == "ROUTES"
                for t in node.targets):
            return tuple(ast.literal_eval(node.value))
    raise RuntimeError("obs/opsd.py has no literal ROUTES table")


def _route_call_names(node: ast.Call, routes) -> List[str]:
    """Unregistered-route findings for one call: a string literal (or
    f-string) as the first argument of ``add_route``/``_add_route``.
    Paths through variables pass — linted at the literal's definition."""
    fn = node.func
    callee = fn.id if isinstance(fn, ast.Name) else (
        fn.attr if isinstance(fn, ast.Attribute) else None)
    if callee not in ("add_route", "_add_route") or not node.args:
        return []
    arg = node.args[0]
    if isinstance(arg, ast.JoinedStr):
        return [f"<f-string> in {callee}()"]
    if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
            and arg.value not in routes:
        return [f"`{arg.value}` in {callee}()"]
    return []


def lint_route_file(path: Path, routes) -> List[Violation]:
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        names = _route_call_names(node, routes)
        if not names:
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if ROUTE_PRAGMA in line:
            continue
        for name in names:
            out.append(Violation(str(path), node.lineno, name, line,
                                 domain="route"))
    return out


def lint_route_package(pkg_root: Path,
                       extra_roots: Tuple[Path, ...] = ()) -> List[Violation]:
    """Lint the whole package tree plus any extra roots (``scripts/``) —
    the route table is what every fleet poller keys on, so no file is
    exempt."""
    routes = load_route_vocab(pkg_root)
    out = []
    paths = sorted(pkg_root.rglob("*.py"))
    for root in extra_roots:
        paths.extend(sorted(root.glob("*.py")))
    for path in paths:
        out.extend(lint_route_file(path, routes))
    return out


def lint_pool_file(path: Path) -> List[Violation]:
    """Attribute READS of the pool's private donated leaves. Writes
    (``x._cache = …``) are equally foreign outside the pool, so any
    ``._cache`` / ``._pad`` attribute node is flagged regardless of
    load/store context — the distinction isn't worth the subtlety."""
    source = path.read_text()
    lines = source.splitlines()
    tree = ast.parse(source, filename=str(path))
    out = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Attribute)
                and node.attr in _POOL_PRIVATE):
            continue
        line = lines[node.lineno - 1] if node.lineno <= len(lines) else ""
        if POOL_PRAGMA in line:
            continue
        out.append(Violation(str(path), node.lineno, f"`.{node.attr}`",
                             line, domain="pool"))
    return out


def lint_pool_package(root: Path) -> List[Violation]:
    """Lint the serving package tree except the pool module itself —
    the only file allowed to touch the donated leaves directly."""
    out = []
    for path in sorted(root.rglob("*.py")):
        if path.name == POOL_SANCTIONED:
            continue
        out.extend(lint_pool_file(path))
    return out


def main(argv: List[str] | None = None) -> List[Violation]:
    args = list(sys.argv[1:] if argv is None else argv)
    pkg_root = Path(__file__).resolve().parent.parent / "elephas_tpu"
    root = Path(args[0]) if args else (pkg_root / "serving")
    violations = lint_package(root)
    if not args:
        violations.extend(lint_pool_package(pkg_root / "serving"))
        violations.extend(lint_pickle_package(pkg_root / "parameter"))
        violations.extend(lint_resilience_package(pkg_root / "resilience"))
        violations.extend(lint_metric_package(pkg_root))
        violations.extend(lint_kind_package(
            pkg_root, extra_roots=(Path(__file__).resolve().parent,)))
        violations.extend(lint_route_package(
            pkg_root, extra_roots=(Path(__file__).resolve().parent,)))
    for v in violations:
        print(v)
    if not violations:
        print(f"lint_blocking: {root} clean")
    return violations


if __name__ == "__main__":
    sys.exit(1 if main() else 0)
