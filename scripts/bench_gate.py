#!/usr/bin/env python
"""Bench gate: diff a fresh bench run against the committed artifact.

The repo commits its measured baselines (``BENCH_SERVE.json``,
``BENCH_PS.json``, ``BENCH_CHAOS.json``, ``BENCH_FLEET.json``); a perf
regression today is
only caught by a human re-reading numbers. This gate makes the diff
mechanical: re-run the bench, hand both files to ``bench_gate.py``, and
get a machine-readable verdict — one check per (row, metric) with the
threshold that was applied, and a process exit code CI can gate on.

Matching: rows are joined on an artifact-specific identity key (serving
rows on ``(mode, pipeline)``, PS rows on ``(mode, codec, op, quantize,
pipelined)``, chaos rows on ``scenario``, fleet rows on ``mode``) —
never on position, so
re-ordered or appended rows don't misalign the diff. A baseline row
missing from the fresh run fails; extra fresh rows are ignored (a new
bench mode is not a regression).

Thresholds are per-metric and directional, deliberately loose: bench
numbers come from shared CI machines, so the gate is tuned to catch
step-change regressions (a 2× transport slowdown, a broken cache, a
serving-overhead blowout past its guardrail), not 5% noise. Throughput
(«higher») metrics may drop to ``1 - rel`` of baseline; latency
(«lower») metrics may grow to ``1 + rel``; ``equal`` metrics (unit
accounting, completion flags) must match exactly; ``limit`` metrics are
absolute ceilings independent of the baseline (the serving trace
overhead guardrail stays < 2% no matter what it measured last time).

Usage:
    python scripts/bench_gate.py --serve BENCH_SERVE.json fresh.json \
        --ps BENCH_PS.json fresh_ps.jsonl \
        --chaos BENCH_CHAOS.json fresh_chaos.jsonl \
        [--out VERDICT.json]

Importable: ``compare(baseline_rows, fresh_rows, kind) -> list[check]``
and ``gate(pairs) -> verdict`` are pure — tests feed them literal rows.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional, Tuple

# -- per-artifact rules ------------------------------------------------------

# Each kind: (key_fields, [(metric, direction, tolerance)]).
# direction: "higher" — fresh >= base*(1-tol); "lower" — fresh <=
# base*(1+tol); "equal" — exact match; "limit" — fresh <= tol (absolute,
# baseline ignored); "floor" — fresh >= tol (absolute lower bound, the
# mirror of "limit").
RULES: Dict[str, Tuple[Tuple[str, ...], List[Tuple[str, str, float]]]] = {
    "serve": (
        ("mode", "pipeline"),
        [
            ("tokens_per_sec", "higher", 0.35),
            ("ttft_s_p95", "lower", 0.60),
            ("itl_s_p95", "lower", 0.60),
            ("all_completed", "equal", 0.0),
            # The serving trace-overhead guardrail is an absolute
            # ceiling: tracing must stay under 2% regardless of what
            # the committed baseline happened to measure.
            ("overhead_pct", "limit", 2.0),
            # Blackbox canary probes ride the real submit path, so
            # their cost is gated with the same discipline: real-
            # traffic throughput with canaries on must stay within 2%
            # of canaries off (measured best-of-rounds, lm_bench
            # --slo).
            ("canary_overhead_pct", "limit", 2.0),
            # Goodput floor on the --slo row: the worst-objective SLO
            # attainment ratio over the bench workload. An absolute
            # floor — at bench scale (unloaded engine, generous
            # thresholds) every request should meet every objective;
            # dipping under 0.9 means latency promises broke or the
            # ledger started counting canaries.
            ("goodput_ratio", "floor", 0.90),
            # Paged-pool --prefix row. The prefix cache must pay for
            # itself on the shared-system-prompt multi-turn workload
            # (absolute floor — below half, resident prefixes are
            # being missed or evicted prematurely); the paged layout
            # must serve the SAME token streams as the contiguous
            # oracle engine (identity is correctness, not perf, same
            # discipline as the fleet router's token_identical); and
            # chunked prefill must keep the decode ITL p99 at or below
            # the unchunked arm's — the chunk budget exists to shrink
            # that tail, a ratio over 1.0 means it traded it away.
            ("prefix_hit_rate", "floor", 0.5),
            ("token_identical", "equal", 0.0),
            ("chunked_itl_ratio", "limit", 1.0),
            # Speculative-decoding --spec row. token_identical reuses
            # the equal-rule above (spec streams must match the
            # unspeculated oracle request-for-request — identity is the
            # whole contract). The accept-rate floor gates the draft
            # MECHANICS on the shared-prefix workload: the bench's
            # PS-delivered draft carries the target's own weights, so
            # anything under ~1.0 means the draft cache, rollback, or
            # frontier bookkeeping broke (breakage there sinks
            # acceptance silently — it never corrupts tokens).
            # tokens_per_step > 1.3 is the reason speculation exists;
            # spec_itl_ratio (per-token: spec step cost / tokens-per-
            # step, over the plain engine's one-token steps) must not
            # trade the latency away.
            ("spec_accept_rate", "floor", 0.5),
            ("tokens_per_step", "floor", 1.3),
            ("spec_itl_ratio", "limit", 1.0),
            # Durable-telemetry row (--store-overhead): overhead_pct is
            # already ceilinged above (the rule table is a superset over
            # row shapes); within_2pct pins the bench's own verdict bit,
            # and the row must prove the store actually journaled during
            # the timed window — an empty journal would make the 2%
            # "overhead" a measurement of nothing.
            ("within_2pct", "equal", 0.0),
            ("journaled_records", "floor", 1.0),
        ],
    ),
    "ps": (
        ("mode", "codec", "op", "quantize", "pipelined"),
        [
            ("mb_per_s", "higher", 0.50),
            ("secs_per_roundtrip", "lower", 0.75),
            ("secs_per_unit", "lower", 0.75),
            ("speedup", "higher", 0.50),
            ("ratio", "higher", 0.50),
            # Shard-group scaling guardrail: the K=4 single-shard-dirty
            # refresh must deliver at least 2x the K=1 effective view
            # bandwidth. Absolute floor — the win is byte economy
            # (K-1 shards answer not-modified), so it holds on any host
            # regardless of core count; a drop below 2 means per-shard
            # version gating or the scatter/gather path broke.
            ("ps_shard_bw_ratio", "floor", 2.0),
        ],
    ),
    "chaos": (
        ("scenario",),
        [
            ("completed_units", "equal", 0.0),
            ("wall_s", "lower", 1.00),
            ("mttr_max_s", "lower", 1.00),
            ("final_loss", "lower", 1.00),
            # Training-health row (--health): applied-delta version lag
            # must not blow up. Lag is a small integer with real
            # scheduling noise at bench scale, so the tolerance is the
            # loosest in the table — it catches order-of-magnitude
            # staleness blowups, not ±1 version jitter.
            ("staleness_p95", "lower", 2.00),
            # Fleet row (--fleet): polling N live ops endpoints + the
            # bucket-wise merge must stay cheap enough to run at a 1 s
            # cadence. Absolute ceilings, same style as the serving
            # trace guardrail — the scrape cost budget doesn't move
            # with whatever a loaded CI machine measured last time.
            ("fleet_scrape_ms_mean", "limit", 150.0),
            ("fleet_merge_ms_mean", "limit", 50.0),
            # Replay-stable outage visibility: the kill_ps fleet row
            # must show the full alive→stale→dead→alive arc.
            ("fleet_saw_outage", "equal", 0.0),
            # Shard-kill row (--shards): wall seconds from killing a
            # shard primary to the first successful pull through the
            # re-resolved client. Absolute ceiling, sized as detection
            # (dead_after ≈ 2x suspect_after) + one exhausted client
            # retry budget (~2.8 s) with generous CI headroom.
            ("shard_failover_mttr_s", "limit", 10.0),
            # Zero acked-update loss: the post-promotion pull must equal
            # the last tree the dead primary acked, replay-stably.
            ("acked_state_recovered", "equal", 0.0),
            # Blackbox visibility of the kill: the PS canary probing
            # through the real sharded-client path must SEE the outage
            # (failed probes on the killed shard) and see it end.
            ("canary_saw_outage", "equal", 0.0),
            # Staleness row (--staleness): the hard admission bound must
            # actually have refused deltas (True, exact — the sweep is
            # seeded and single-threaded, so this is replay-stable, not
            # a flaky count), bounding staleness must never converge
            # WORSE than unbounded (absolute floor at 0 on
            # loss(inf) - loss(max=2)), and the swept final trees must
            # replay bit-identically.
            ("staleness_rejected_nonzero", "equal", 0.0),
            ("staleness_recovery_gain", "floor", 0.0),
            ("staleness_digest", "equal", 0.0),
            # Post-mortem row (--postmortem): the incident rebuilt from
            # disk alone — after every process was hard-killed — must
            # name the shard kill as the triggering event, rebuild a
            # non-empty timeline, and produce the SAME digest twice in
            # one run (replay stability) and across runs (the pinned
            # incident_digest, exact like staleness_digest: the arc is
            # seeded and monitor-free). Zero corrupt tails: clean kills
            # close their segment, so a torn frame here means the
            # store's write path broke, not the crash model.
            ("postmortem_rebuilt", "equal", 0.0),
            ("digest_replay_stable", "equal", 0.0),
            ("incident_digest", "equal", 0.0),
            ("triggering_event", "equal", 0.0),
            ("trigger_is_shard_kill", "equal", 0.0),
            ("corrupt_tails", "equal", 0.0),
            # Steady-state persistence tax on the PS push path: same
            # absolute-ceiling discipline as the serving trace/canary
            # guardrails — journaling telemetry must stay under 2%
            # regardless of what the committed baseline measured.
            ("store_overhead_pct", "limit", 2.0),
            ("store_overhead_within_2pct", "equal", 0.0),
            # Tuner row (--tune): the chaos search (worker killed
            # mid-rung + checkpoint-shard primary crashed mid-search)
            # must reproduce the undisturbed reference EXACTLY — same
            # winner digest, same search digest (winner trajectory +
            # ladder), zero trials lost — because ASHA's promotion rule
            # is order-invariant for the minimum-loss chain. The
            # injected kill and the shard failover must actually have
            # fired (a chaos arm that didn't hurt anything gates
            # nothing), halving must have pruned most of the field, and
            # the spent budget must do at least as well as the same
            # budget given to full-ladder random trials. Absolute
            # floors/equals throughout: none of these move with
            # whatever a loaded CI machine measured last time.
            ("tune_winner_stable", "equal", 0.0),
            ("tune_search_digest_stable", "equal", 0.0),
            ("tune_lost_trials", "equal", 0.0),
            ("tune_ps_kill_fired", "equal", 0.0),
            ("tune_final_pull_ok", "equal", 0.0),
            ("tune_worker_deaths", "floor", 1.0),
            ("tune_ps_failovers", "floor", 1.0),
            ("tune_pruned_frac", "floor", 0.5),
            ("tune_epochs_saved_frac", "floor", 0.5),
            ("tune_loss_advantage", "floor", 0.0),
            # The digests themselves are pinned exact (same style as
            # staleness_digest): the trial set is seeded, so the winner
            # identity and its rung-loss trajectory must replay
            # bit-stably across machines, not just within one run.
            ("winner_digest", "equal", 0.0),
            ("search_digest", "equal", 0.0),
        ],
    ),
    "analysis": (
        ("section",),
        [
            # Static-analysis gate (ANALYSIS.json `rows`): zero
            # unsuppressed violations is an absolute ceiling — a fresh
            # finding fails the gate no matter what the committed
            # baseline says. Suppression counts are exact per rule: a
            # NEW pragma (someone silencing a finding) and a VANISHED
            # one (an escape rotted away) both surface as a diff that
            # has to be re-committed deliberately. Same discipline for
            # the lock graph: a fresh lock-order cycle is an absolute
            # fail, and the graph's shape (lock and edge counts) moving
            # means the concurrency structure changed — re-baseline
            # consciously.
            ("violations", "limit", 0.0),
            ("suppressions", "equal", 0.0),
            ("lock_cycles", "limit", 0.0),
            ("locks", "equal", 0.0),
            ("lock_edges", "equal", 0.0),
        ],
    ),
    "fleet": (
        ("mode",),
        [
            # Routed-vs-bare guardrail: one replica behind the router
            # must cost < 2% throughput vs the bare engine — same
            # absolute-ceiling discipline as the trace/canary
            # overheads, measured with the same best-of-rounds
            # alternation.
            ("routed_overhead_pct", "limit", 2.0),
            # And the routed stream must be the SAME stream: token
            # identity is the router's correctness proof, not a perf
            # number.
            ("token_identical", "equal", 0.0),
            ("tokens_per_sec", "higher", 0.35),
            ("all_completed", "equal", 0.0),
            # Session affinity must actually hold under steady
            # multi-turn traffic: a follow-up turn that re-prefills on
            # a different replica is wasted work the signals should
            # have prevented. Absolute floor, not baseline-relative.
            ("affinity_hit_rate", "floor", 0.90),
            # Kill-mid-traffic row: the fleet plane must SEE the
            # replica die (dead in its transition arc) and come back,
            # replay-stably — the fleet_saw_outage discipline applied
            # to a serving replica.
            ("fleet_saw_replica_outage", "equal", 0.0),
            # Blackbox outage as the router's clients experience it:
            # canary probes routed through the fleet during the kill.
            # Ceiling sized as kill detection (one result slice) plus
            # requeue + re-prefill of the probe, with CI headroom.
            ("outage_canary_s", "limit", 10.0),
            # Real-goodput dip bound for the same window: requeued
            # requests pay dispatch+re-prefill once, they don't fail —
            # worst-objective attainment stays above half even while
            # the fleet is one replica down.
            ("goodput_ratio_after_kill", "floor", 0.50),
            # Autoscaler proof bits: under the seeded burst the
            # decision sequence must contain the scale-up, and the
            # post-cooldown quiet window must produce the scale-down.
            ("scaled_up_under_burst", "equal", 0.0),
            ("scaled_down_after_cooldown", "equal", 0.0),
            # Tenancy row (--tenants). Attribution must CONSERVE: the
            # per-tenant prefill+decode token sums must equal the
            # engine's fleet totals exactly (the committed value is
            # 0.0 and the equal-rule holds it there — any leak, double
            # bill, or dropped tag shows up as a nonzero diff). Tagging
            # must be free under the same 2% absolute ceiling as every
            # other observability plane, the interactive tenant's
            # goodput must stay above an absolute floor even while the
            # batch tenant saturates the pool, and the committed
            # exemplar-to-trace join bit must stay true (a histogram
            # p99 that can't name a span tree is a dead end).
            ("tenant_token_conservation", "equal", 0.0),
            ("tenant_overhead_pct", "limit", 2.0),
            ("interactive_goodput_ratio", "floor", 0.25),
            ("tenant_exemplar_joined", "equal", 0.0),
            # Disaggregated-tiers row (--disagg). token_identical reuses
            # the equal-rule above: the tiered fleet must serve byte-
            # equal streams to the monolithic fleet — handoff is a
            # transport, not a resample. The ITL-interference ratio is
            # the reason the tiers exist: decode-tier ITL p99 under
            # long-prompt interference must not EXCEED the monolithic
            # fleet's (<= 1.0 is the hard line; the committed number
            # should sit well below it). Handoff latency is an absolute
            # ceiling sized as encode + one cross-engine import step
            # with CI headroom — it must not move with whatever a loaded
            # machine measured last time. The cross-tier prefix floor
            # holds the shared-system-prompt hit discipline across the
            # handoff boundary (same 0.5 floor as the single-engine
            # --prefix row), and the fair-share floor pins the worst
            # tenant's goodput while the batch tenant saturates the
            # prefill tier.
            ("disagg_itl_p99_ratio", "limit", 1.0),
            ("handoff_p99_ms", "limit", 250.0),
            ("cross_tier_prefix_hit_rate", "floor", 0.5),
            ("goodput_floor_min_tenant", "floor", 0.25),
            # Live-model-delivery row (--rollout). token_identical
            # reuses the equal-rule above for the zero-delta phase: a
            # mid-stream swap to byte-identical weights must not change
            # one emitted token vs the no-swap oracle — the swap seam
            # is atomic or it is broken. The swap tax is a ratio of ITL
            # p99 with a per-step version-gated subscriber against the
            # no-subscriber fleet: steady state is K not-modified
            # frames, so anything past 1.5x means the gate leaked full
            # transfers onto the serving path. rollback_served_stale
            # counts non-canary replicas ever OBSERVED at the poisoned
            # version during the forced-rollback phase — the canary
            # blast-radius proof, held at exactly zero. The goodput
            # floor spans the whole arc: a live trainer pushing through
            # canary, promote AND rollback must not cost the fleet its
            # worst-objective attainment.
            ("swap_itl_p99_ratio", "limit", 1.5),
            ("rollback_served_stale", "equal", 0.0),
            ("rollout_goodput_ratio", "floor", 0.50),
            ("rollout_promoted", "equal", 0.0),
            ("rollout_rolled_back", "equal", 0.0),
        ],
    ),
}


def load_rows(path: str) -> List[dict]:
    """A JSON array, JSONL, or a report dict carrying a ``rows`` table
    (``ANALYSIS.json``) — all three artifact shapes exist."""
    with open(path) as f:
        text = f.read().strip()
    if not text:
        return []
    if text[0] == "[":
        return json.loads(text)
    try:
        doc = json.loads(text)
    except ValueError:
        # multi-record JSONL: one dict per line
        return [json.loads(line) for line in text.splitlines()
                if line.strip()]
    if isinstance(doc, dict) and "rows" in doc:
        return doc["rows"]
    return [doc]


def _row_key(row: dict, fields: Tuple[str, ...]) -> Tuple:
    return tuple(str(row.get(f)) for f in fields)


def _check(metric: str, direction: str, tol: float,
           base, fresh) -> Tuple[bool, str]:
    if direction == "equal":
        return fresh == base, f"must equal {base!r}"
    if direction == "limit":
        return float(fresh) <= tol, f"must be <= {tol}"
    if direction == "floor":
        return float(fresh) >= tol, f"must be >= {tol}"
    if direction == "higher":
        floor = float(base) * (1.0 - tol)
        return float(fresh) >= floor, f"must be >= {floor:.6g}"
    if direction == "lower":
        ceil = float(base) * (1.0 + tol)
        return float(fresh) <= ceil, f"must be <= {ceil:.6g}"
    raise ValueError(f"unknown direction {direction!r}")


def compare(baseline_rows: List[dict], fresh_rows: List[dict],
            kind: str) -> List[dict]:
    """Pure diff: one check dict per (baseline row, applicable metric).

    A check is ``{"kind", "key", "metric", "baseline", "fresh",
    "threshold", "ok"}``; a baseline row absent from the fresh run
    yields a single failing ``row_present`` check. Metrics absent from
    a baseline row don't apply to it (the rule table is a superset over
    all row shapes of the artifact).
    """
    key_fields, metric_rules = RULES[kind]
    fresh_by_key = {_row_key(r, key_fields): r for r in fresh_rows}
    checks: List[dict] = []
    for base_row in baseline_rows:
        key = _row_key(base_row, key_fields)
        applicable = [
            (m, d, t) for m, d, t in metric_rules
            if m in base_row and base_row[m] is not None
        ]
        if not applicable:
            continue  # meta rows carry config, not gated metrics
        fresh_row = fresh_by_key.get(key)
        label = "/".join(k for k in key if k != "None")
        if fresh_row is None:
            checks.append({
                "kind": kind, "key": label, "metric": "row_present",
                "baseline": True, "fresh": False,
                "threshold": "row must exist in fresh run", "ok": False,
            })
            continue
        for metric, direction, tol in applicable:
            fresh_val = fresh_row.get(metric)
            if fresh_val is None:
                ok, desc = False, "metric missing from fresh run"
            else:
                ok, desc = _check(metric, direction, tol,
                                  base_row[metric], fresh_val)
            checks.append({
                "kind": kind, "key": label, "metric": metric,
                "baseline": base_row[metric], "fresh": fresh_val,
                "threshold": desc, "ok": ok,
            })
    return checks


def gate(pairs: Dict[str, Tuple[List[dict], List[dict]]]) -> dict:
    """Run ``compare`` per artifact kind; roll up a machine-readable
    verdict: ``{"verdict": "pass"|"fail", "checks": N, "failures":
    [...failing checks...], "by_kind": {kind: {checks, failures}}}``."""
    all_checks: List[dict] = []
    by_kind = {}
    for kind, (baseline_rows, fresh_rows) in pairs.items():
        checks = compare(baseline_rows, fresh_rows, kind)
        by_kind[kind] = {
            "checks": len(checks),
            "failures": sum(1 for c in checks if not c["ok"]),
        }
        all_checks.extend(checks)
    failures = [c for c in all_checks if not c["ok"]]
    return {
        "verdict": "fail" if failures else "pass",
        "checks": len(all_checks),
        "failures": failures,
        "by_kind": by_kind,
    }


def main(argv: Optional[List[str]] = None) -> dict:
    ap = argparse.ArgumentParser(
        description="Gate fresh bench output against committed baselines"
    )
    for kind in RULES:
        ap.add_argument(
            f"--{kind}", nargs=2, metavar=("BASELINE", "FRESH"),
            default=None, help=f"{kind} artifact pair to diff",
        )
    ap.add_argument("--out", default=None,
                    help="write the verdict JSON here too")
    args = ap.parse_args(argv)
    pairs = {}
    for kind in RULES:
        pair = getattr(args, kind)
        if pair is not None:
            pairs[kind] = (load_rows(pair[0]), load_rows(pair[1]))
    if not pairs:
        ap.error("give at least one of --serve/--ps/--chaos/--fleet")
    verdict = gate(pairs)
    text = json.dumps(verdict, indent=1)
    print(text)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text + "\n")
    if verdict["verdict"] != "pass":
        sys.exit(1)
    return verdict


if __name__ == "__main__":
    main()
