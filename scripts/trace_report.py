#!/usr/bin/env python
"""Per-phase latency report over a Chrome ``trace_event`` JSON file.

Reads the trace the obs tracer exports (``Tracer.export_chrome`` /
``scripts/lm_bench.py --trace``) back into numbers a human can act on:

- a per-phase table — count, p50/p90/p95/p99, mean, total wall — over
  every duration ("X") event name. Percentiles here are EXACT (the file
  holds every sample), unlike the registry's bucketed estimates, so
  this is also the oracle the histogram tests pin against.
- one reconstructed per-request span tree: the busiest ``req:<id>``
  track's events nested by time containment — the submit→queue→admit
  (prefill)→decode→finish lifecycle, as the scheduler recorded it.

Usage: ``python scripts/trace_report.py TRACE.json [--tree-req ID]``
(importable: ``report(path) -> str`` and ``main(argv)``).
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Dict, List, Optional


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if e.get("ph") == "X"]


def track_names(path: str) -> Dict[int, str]:
    """tid → thread-name from the trace's "M" metadata events."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return {
        e["tid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }


def percentile(sorted_vals: List[float], q: float) -> float:
    """Exact linear-interpolated quantile of an ASCENDING sample list."""
    if not sorted_vals:
        raise ValueError("empty sample list")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * frac


def phase_table(events: List[dict]) -> List[dict]:
    """One row per span name: count + exact latency percentiles (s),
    sorted by total wall descending."""
    by_name: Dict[str, List[float]] = {}
    for e in events:
        if e.get("dur", 0) <= 0:
            continue  # instants carry no duration signal
        by_name.setdefault(e["name"], []).append(e["dur"] / 1e6)
    rows = []
    for name, vals in by_name.items():
        vals.sort()
        rows.append({
            "phase": name,
            "count": len(vals),
            "p50_s": percentile(vals, 0.50),
            "p90_s": percentile(vals, 0.90),
            "p95_s": percentile(vals, 0.95),
            "p99_s": percentile(vals, 0.99),
            "mean_s": sum(vals) / len(vals),
            "total_s": sum(vals),
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def build_tree(events: List[dict]) -> List[dict]:
    """Nest one track's events by time containment: parent = the
    innermost longer span whose [ts, ts+dur] covers the child's."""
    nodes = [
        {"event": e, "start": e["ts"], "end": e["ts"] + e.get("dur", 0),
         "children": []}
        for e in events
    ]
    # Outermost first: earlier start, then longer duration, so a stack
    # walk assigns each node to the deepest still-open enclosing span.
    nodes.sort(key=lambda n: (n["start"], -(n["end"] - n["start"])))
    roots: List[dict] = []
    stack: List[dict] = []
    eps = 1.0  # µs slack: clock reads inside a span can tie its edges
    for node in nodes:
        while stack and node["start"] > stack[-1]["end"] + eps:
            stack.pop()
        while stack and node["end"] > stack[-1]["end"] + eps:
            stack.pop()  # overlaps but not contained: not a child
        (stack[-1]["children"] if stack else roots).append(node)
        stack.append(node)
    return roots


def pick_request_track(events: List[dict], names: Dict[int, str],
                       req_id: Optional[int] = None) -> Optional[int]:
    """The tid to draw the sample tree from: the requested ``req:<id>``
    track, else the busiest completed-request track."""
    req_tids = {t for t, n in names.items() if n.startswith("req:")}
    if req_id is not None:
        want = f"req:{req_id}"
        for tid, name in names.items():
            if name == want:
                return tid
        return None
    best, best_key = None, (-1, -1)
    for tid in req_tids:
        evs = [e for e in events if e["tid"] == tid]
        done = any(
            e["name"] == "request"
            and (e.get("args") or {}).get("status") == "completed"
            for e in evs
        )
        try:
            rid = int(names[tid].split(":", 1)[1])
        except ValueError:
            rid = -1
        # Tie-break toward the LATEST request: early ones carry XLA
        # compile inside prefill and misrepresent steady state.
        if done and (len(evs), rid) > best_key:
            best, best_key = tid, (len(evs), rid)
    return best


def format_tree(roots: List[dict], indent: str = "") -> List[str]:
    lines = []
    for node in roots:
        e = node["event"]
        dur_ms = e.get("dur", 0) / 1e3
        args = e.get("args") or {}
        extra = " ".join(
            f"{k}={v}" for k, v in args.items() if k != "req_id"
        )
        what = (
            f"@{e['ts'] / 1e3:.3f}ms" if e.get("dur", 0) == 0
            else f"{dur_ms:.3f}ms"
        )
        lines.append(f"{indent}{e['name']:<12} {what}"
                     + (f"  [{extra}]" if extra else ""))
        lines.extend(format_tree(node["children"], indent + "  "))
    return lines


def report(path: str, req_id: Optional[int] = None) -> str:
    events = load_events(path)
    names = track_names(path)
    out = [f"# Trace report: {path}", ""]
    if not events:
        out.append("(no duration events)")
        return "\n".join(out)
    window_s = (
        max(e["ts"] + e.get("dur", 0) for e in events)
        - min(e["ts"] for e in events)
    ) / 1e6
    n_req = sum(1 for n in names.values() if n.startswith("req:"))
    out.append(
        f"{len(events)} span events over {window_s:.3f}s across "
        f"{len(names)} tracks ({n_req} request lanes)"
    )
    out += ["", "## Per-phase latency (seconds, exact percentiles)", ""]
    header = (f"{'phase':<22}{'count':>7}{'p50':>11}{'p90':>11}"
              f"{'p95':>11}{'p99':>11}{'mean':>11}{'total':>11}")
    out += [header, "-" * len(header)]
    for r in phase_table(events):
        out.append(
            f"{r['phase']:<22}{r['count']:>7}"
            f"{r['p50_s']:>11.6f}{r['p90_s']:>11.6f}{r['p95_s']:>11.6f}"
            f"{r['p99_s']:>11.6f}{r['mean_s']:>11.6f}{r['total_s']:>11.4f}"
        )
    tid = pick_request_track(events, names, req_id)
    if tid is not None:
        out += ["", f"## Sample request lifecycle ({names[tid]})", ""]
        tree = build_tree([e for e in events if e["tid"] == tid])
        out.extend(format_tree(tree))
    return "\n".join(out) + "\n"


def main(argv: Optional[List[str]] = None) -> str:
    parser = argparse.ArgumentParser(
        description="Per-phase percentiles + request tree from a trace"
    )
    parser.add_argument("trace", help="Chrome trace_event JSON file")
    parser.add_argument("--tree-req", type=int, default=None,
                        help="draw the tree for this req_id")
    parser.add_argument("--out", default=None,
                        help="also write the report to this file")
    args = parser.parse_args(argv)
    text = report(args.trace, req_id=args.tree_req)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text, end="")
    return text


if __name__ == "__main__":
    main()
