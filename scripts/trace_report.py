#!/usr/bin/env python
"""Per-phase latency report + multi-process merger over Chrome traces.

Reads the trace the obs tracer exports (``Tracer.export_chrome`` /
``scripts/lm_bench.py --trace`` / a live ``/trace`` opsd route) back
into numbers a human can act on:

- a per-phase table — count, p50/p90/p95/p99, mean, total wall — over
  every duration ("X") event name. Percentiles here are EXACT (the file
  holds every sample), unlike the registry's bucketed estimates, so
  this is also the oracle the histogram tests pin against.
- one reconstructed per-request span tree: the busiest ``req:<id>``
  track's events nested by time containment — the submit→queue→admit
  (prefill)→decode→finish lifecycle, as the scheduler recorded it.

Merge mode (``--merge DUMP...``) collects per-process dumps — each
normalized to its own t=0 in its own monotonic clock domain — into ONE
trace on a shared wall-clock axis: every dump carries a ``clockSync``
block (``origin_mono_s`` plus a simultaneous (mono, wall) sample taken
at export), so an event's wall time is
``wall_at_export - mono_at_export + origin_mono_s + ts``. Each dump
becomes its own pid row (named via the dump's ``process`` field), and
because the parameter-server wire codec propagates ``(trace_id,
span_id)``, a worker's ``ps/push`` and the PS-side ``ps/handle_push``/
``ps/apply`` spans join on ``args.trace_id`` across the process
boundary. On top of the join, ``--merge`` prints the per-unit
critical-path table — queue (comms backlog) vs wire (client round
trips) vs lock (PS apply under the buffer lock) vs train — with the
straggler unit first, plus a replay-stable digest over the set of
completed units (seeded ``FaultPlan`` chaos runs reproduce it).

Usage:
    python scripts/trace_report.py TRACE.json [--tree-req ID]
        [--tenant ID]
    python scripts/trace_report.py --merge D1.json D2.json...
        [--out MERGED.json]
(importable: ``report(path) -> str``, ``merge_dumps``, ``unit_table``,
``unit_chain_digest``, and ``main(argv)``).
"""

from __future__ import annotations

import argparse
import json
import sys
import zlib
from typing import Dict, List, Optional, Union


def load_events(path: str) -> List[dict]:
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return [e for e in events if e.get("ph") == "X"]


def track_names(path: str) -> Dict[int, str]:
    """tid → thread-name from the trace's "M" metadata events."""
    with open(path) as f:
        doc = json.load(f)
    events = doc["traceEvents"] if isinstance(doc, dict) else doc
    return {
        e["tid"]: e["args"]["name"]
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
    }


def percentile(sorted_vals: List[float], q: float) -> float:
    """Exact linear-interpolated quantile of an ASCENDING sample list."""
    if not sorted_vals:
        raise ValueError("empty sample list")
    if len(sorted_vals) == 1:
        return sorted_vals[0]
    pos = q * (len(sorted_vals) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_vals) - 1)
    frac = pos - lo
    return sorted_vals[lo] + (sorted_vals[hi] - sorted_vals[lo]) * frac


def phase_table(events: List[dict]) -> List[dict]:
    """One row per span name: count + exact latency percentiles (s),
    sorted by total wall descending."""
    by_name: Dict[str, List[float]] = {}
    for e in events:
        if e.get("dur", 0) <= 0:
            continue  # instants carry no duration signal
        by_name.setdefault(e["name"], []).append(e["dur"] / 1e6)
    rows = []
    for name, vals in by_name.items():
        vals.sort()
        rows.append({
            "phase": name,
            "count": len(vals),
            "p50_s": percentile(vals, 0.50),
            "p90_s": percentile(vals, 0.90),
            "p95_s": percentile(vals, 0.95),
            "p99_s": percentile(vals, 0.99),
            "mean_s": sum(vals) / len(vals),
            "total_s": sum(vals),
        })
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def build_tree(events: List[dict]) -> List[dict]:
    """Nest one track's events by time containment: parent = the
    innermost longer span whose [ts, ts+dur] covers the child's."""
    nodes = [
        {"event": e, "start": e["ts"], "end": e["ts"] + e.get("dur", 0),
         "children": []}
        for e in events
    ]
    # Outermost first: earlier start, then longer duration, so a stack
    # walk assigns each node to the deepest still-open enclosing span.
    nodes.sort(key=lambda n: (n["start"], -(n["end"] - n["start"])))
    roots: List[dict] = []
    stack: List[dict] = []
    eps = 1.0  # µs slack: clock reads inside a span can tie its edges
    for node in nodes:
        while stack and node["start"] > stack[-1]["end"] + eps:
            stack.pop()
        while stack and node["end"] > stack[-1]["end"] + eps:
            stack.pop()  # overlaps but not contained: not a child
        (stack[-1]["children"] if stack else roots).append(node)
        stack.append(node)
    return roots


def pick_request_track(events: List[dict], names: Dict[int, str],
                       req_id: Optional[int] = None) -> Optional[int]:
    """The tid to draw the sample tree from: the requested ``req:<id>``
    track, else the busiest completed-request track."""
    req_tids = {t for t, n in names.items() if n.startswith("req:")}
    if req_id is not None:
        want = f"req:{req_id}"
        for tid, name in names.items():
            if name == want:
                return tid
        return None
    best, best_key = None, (-1, -1)
    for tid in req_tids:
        evs = [e for e in events if e["tid"] == tid]
        done = any(
            e["name"] == "request"
            and (e.get("args") or {}).get("status") == "completed"
            for e in evs
        )
        try:
            rid = int(names[tid].split(":", 1)[1])
        except ValueError:
            rid = -1
        # Tie-break toward the LATEST request: early ones carry XLA
        # compile inside prefill and misrepresent steady state.
        if done and (len(evs), rid) > best_key:
            best, best_key = tid, (len(evs), rid)
    return best


def tenant_tracks(events: List[dict], names: Dict[int, str],
                  tenant: str) -> set:
    """tids of ``req:<id>`` tracks belonging to ``tenant``: the
    scheduler stamps every ``request`` span (and the engine every
    ``submit`` instant) with a ``tenant`` arg, untagged requests as
    ``default`` — so membership is read off the events themselves."""
    tids = set()
    for e in events:
        if (e.get("args") or {}).get("tenant") != tenant:
            continue
        if names.get(e["tid"], "").startswith("req:"):
            tids.add(e["tid"])
    return tids


def format_tree(roots: List[dict], indent: str = "") -> List[str]:
    lines = []
    for node in roots:
        e = node["event"]
        dur_ms = e.get("dur", 0) / 1e3
        args = e.get("args") or {}
        extra = " ".join(
            f"{k}={v}" for k, v in args.items() if k != "req_id"
        )
        what = (
            f"@{e['ts'] / 1e3:.3f}ms" if e.get("dur", 0) == 0
            else f"{dur_ms:.3f}ms"
        )
        lines.append(f"{indent}{e['name']:<12} {what}"
                     + (f"  [{extra}]" if extra else ""))
        lines.extend(format_tree(node["children"], indent + "  "))
    return lines


def report(path: str, req_id: Optional[int] = None,
           tenant: Optional[str] = None) -> str:
    events = load_events(path)
    names = track_names(path)
    out = [f"# Trace report: {path}", ""]
    if tenant is not None:
        # One tenant's view: phase table and tree restricted to the
        # request tracks whose spans carry this tenant tag.
        tids = tenant_tracks(events, names, tenant)
        events = [e for e in events if e["tid"] in tids]
        out[0] += f" (tenant={tenant}, {len(tids)} request lanes)"
        if not tids:
            out.append(f"(no request tracks tagged tenant={tenant})")
            return "\n".join(out) + "\n"
    if not events:
        out.append("(no duration events)")
        return "\n".join(out)
    window_s = (
        max(e["ts"] + e.get("dur", 0) for e in events)
        - min(e["ts"] for e in events)
    ) / 1e6
    n_req = sum(1 for n in names.values() if n.startswith("req:"))
    out.append(
        f"{len(events)} span events over {window_s:.3f}s across "
        f"{len(names)} tracks ({n_req} request lanes)"
    )
    out += ["", "## Per-phase latency (seconds, exact percentiles)", ""]
    header = (f"{'phase':<22}{'count':>7}{'p50':>11}{'p90':>11}"
              f"{'p95':>11}{'p99':>11}{'mean':>11}{'total':>11}")
    out += [header, "-" * len(header)]
    for r in phase_table(events):
        out.append(
            f"{r['phase']:<22}{r['count']:>7}"
            f"{r['p50_s']:>11.6f}{r['p90_s']:>11.6f}{r['p95_s']:>11.6f}"
            f"{r['p99_s']:>11.6f}{r['mean_s']:>11.6f}{r['total_s']:>11.4f}"
        )
    tid = pick_request_track(events, names, req_id)
    if tid is not None:
        out += ["", f"## Sample request lifecycle ({names[tid]})", ""]
        tree = build_tree([e for e in events if e["tid"] == tid])
        out.extend(format_tree(tree))
    return "\n".join(out) + "\n"


# -- multi-process merge ----------------------------------------------------


def _load_doc(dump: Union[str, dict]) -> dict:
    if isinstance(dump, str):
        with open(dump) as f:
            return json.load(f)
    return dump


def _wall_base(doc: dict) -> Optional[float]:
    """Wall-clock seconds of the dump's normalized t=0, from its
    ``clockSync`` block: the (mono, wall) pair sampled at export maps
    the recording clock to wall time, and ``origin_mono_s`` is t=0 in
    the recording clock."""
    cs = doc.get("clockSync")
    if not cs:
        return None
    return (cs["wall_s_at_export"] - cs["mono_s_at_export"]
            + cs["origin_mono_s"])


def merge_dumps(dumps: List[Union[str, dict]], out: Optional[str] = None,
                names: Optional[List[str]] = None) -> dict:
    """Merge per-process Chrome-trace dumps onto one wall-clock axis.

    Each dump becomes its own pid (with a ``process_name`` metadata row
    from the dump's ``process`` field / ``names``); "X" events are
    shifted by the dump's clockSync offset so simultaneous wall-clock
    moments in different processes line up, then re-normalized so the
    earliest event across ALL dumps sits at t=0. ``droppedSpans``
    totals are summed — a merged trace built from lossy rings says so.
    """
    docs = [_load_doc(d) for d in dumps]
    bases: List[Optional[float]] = []
    for i, doc in enumerate(docs):
        has_events = any(
            e.get("ph") == "X" for e in doc.get("traceEvents", ())
        )
        base = _wall_base(doc) if has_events else None
        if has_events and base is None:
            raise ValueError(
                f"dump {i} has span events but no clockSync block; "
                "cannot align clocks (re-export with export_chrome)"
            )
        bases.append(base)
    live = [b for b in bases if b is not None]
    t0 = min(live) if live else 0.0
    merged: List[dict] = []
    dropped = 0
    proc_names = []
    for pid, (doc, base) in enumerate(zip(docs, bases), start=1):
        name = doc.get("process")
        if names is not None and names[pid - 1]:
            name = names[pid - 1]
        if not name:
            name = f"proc{pid}"
        proc_names.append(name)
        merged.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": name}})
        for e in doc.get("traceEvents", ()):
            e = dict(e)
            e["pid"] = pid
            if e.get("ph") == "X":
                e["ts"] = (base - t0) * 1e6 + e["ts"]
            merged.append(e)
        dropped += int(doc.get("droppedSpans", 0))
    result = {
        "traceEvents": merged,
        "displayTimeUnit": "ms",
        "mergedFrom": proc_names,
        "droppedSpans": dropped,
    }
    if out is not None:
        with open(out, "w") as f:
            json.dump(result, f)
    return result


# The per-unit critical-path decomposition: span names owned by each
# phase. "wire" is the CLIENT's view of a round trip (it contains the
# server's handle time plus the socket itself); "lock" is the PS-side
# apply under the buffer lock (+ WAL durability).
_UNIT_PHASES = (
    ("queue", ("comms/queued",)),
    ("wire", ("ps/pull", "ps/push")),
    ("lock", ("ps/apply",)),
    ("train", ("async/train",)),
)


def unit_table(doc: Union[str, dict]) -> List[dict]:
    """Per-(epoch, partition) critical-path rows from a (merged) trace:
    every span carrying a ``trace_id`` joins its unit's ``async/unit``
    root — including PS-side spans from another process's dump — and the
    unit's wall splits into queue / wire / lock / train / other.
    Sorted straggler-first (longest total)."""
    doc = _load_doc(doc)
    events = [e for e in doc.get("traceEvents", ())
              if e.get("ph") == "X" and (e.get("args") or {}).get("trace_id")]
    by_trace: Dict[str, List[dict]] = {}
    for e in events:
        by_trace.setdefault(e["args"]["trace_id"], []).append(e)
    rows = []
    for trace_id, evs in by_trace.items():
        root = next((e for e in evs if e["name"] == "async/unit"), None)
        if root is None:
            continue  # a serving request or orphan fragment, not a unit
        args = root.get("args") or {}

        def total(names):
            return sum(
                e.get("dur", 0) for e in evs if e["name"] in names
            ) / 1e6

        row = {
            "trace": trace_id[:8],
            "epoch": args.get("epoch"),
            "partition": args.get("partition"),
            "worker": args.get("worker"),
            "spans": len(evs),
        }
        accounted = 0.0
        for phase, names in _UNIT_PHASES:
            row[f"{phase}_s"] = total(names)
            accounted += row[f"{phase}_s"]
        row["total_s"] = root.get("dur", 0) / 1e6
        row["other_s"] = max(row["total_s"] - accounted, 0.0)
        rows.append(row)
    rows.sort(key=lambda r: -r["total_s"])
    return rows


def unit_chain_digest(doc: Union[str, dict]) -> int:
    """Order-independent digest over the SET of completed units (their
    ``(epoch, partition)`` identities — never the random trace ids or
    timings), so two replays of the same seeded ``FaultPlan`` chaos run
    produce the same value even though threads interleave differently.
    A re-queued unit re-run by a survivor dedupes into one entry."""
    doc = _load_doc(doc)
    units = set()
    for e in doc.get("traceEvents", ()):
        if e.get("ph") != "X" or e.get("name") != "async/unit":
            continue
        args = e.get("args") or {}
        if args.get("epoch") is not None and args.get("partition") is not None:
            units.add((str(args["epoch"]), str(args["partition"])))
    digest = 0
    for epoch, part in units:
        digest ^= zlib.crc32(f"{epoch}/{part}".encode())
    return digest & 0xFFFFFFFF


def format_unit_table(rows: List[dict]) -> List[str]:
    header = (f"{'unit':<12}{'worker':>8}{'queue':>10}{'wire':>10}"
              f"{'lock':>10}{'train':>10}{'other':>10}{'total':>10}"
              f"{'spans':>7}")
    lines = [header, "-" * len(header)]
    for i, r in enumerate(rows):
        unit = f"e{r['epoch']}/p{r['partition']}"
        mark = " <- straggler" if i == 0 and len(rows) > 1 else ""
        lines.append(
            f"{unit:<12}{str(r['worker']):>8}"
            f"{r['queue_s']:>10.4f}{r['wire_s']:>10.4f}{r['lock_s']:>10.4f}"
            f"{r['train_s']:>10.4f}{r['other_s']:>10.4f}"
            f"{r['total_s']:>10.4f}{r['spans']:>7}{mark}"
        )
    return lines


def merge_report(dumps: List[str], out: Optional[str] = None) -> str:
    merged = merge_dumps(dumps, out=out)
    n_span = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
    lines = [
        f"# Merged trace: {len(dumps)} dumps "
        f"({', '.join(merged['mergedFrom'])}), {n_span} span events",
    ]
    if merged["droppedSpans"]:
        lines.append(f"WARNING: {merged['droppedSpans']} spans were "
                     "dropped by bounded rings before export")
    if out:
        lines.append(f"wrote {out}")
    rows = unit_table(merged)
    if rows:
        lines += ["", "## Per-unit critical path (seconds)", ""]
        lines += format_unit_table(rows)
        lines += ["", f"unit_chain_digest: "
                      f"{unit_chain_digest(merged):#010x} "
                      f"({len(rows)} unit traces)"]
    else:
        lines.append("(no async/unit traces — nothing to decompose)")
    return "\n".join(lines) + "\n"


def main(argv: Optional[List[str]] = None) -> str:
    parser = argparse.ArgumentParser(
        description="Per-phase percentiles + request tree from a trace, "
                    "or a clock-aligned multi-process merge (--merge)"
    )
    parser.add_argument("trace", nargs="+",
                        help="Chrome trace_event JSON file(s)")
    parser.add_argument("--merge", action="store_true",
                        help="merge per-process dumps (clockSync-aligned) "
                             "and print the per-unit critical-path table")
    parser.add_argument("--tree-req", type=int, default=None,
                        help="draw the tree for this req_id")
    parser.add_argument("--tenant", default=None,
                        help="restrict the phase table and tree to one "
                             "tenant's request tracks (untagged "
                             "requests are tenant 'default')")
    parser.add_argument("--out", default=None,
                        help="write the merged trace (--merge) or the "
                             "report text to this file")
    args = parser.parse_args(argv)
    if args.merge:
        text = merge_report(args.trace, out=args.out)
        print(text, end="")
        return text
    if len(args.trace) > 1:
        parser.error("multiple trace files require --merge")
    text = report(args.trace[0], req_id=args.tree_req,
                  tenant=args.tenant)
    if args.out:
        with open(args.out, "w") as f:
            f.write(text)
    print(text, end="")
    return text


if __name__ == "__main__":
    main()
