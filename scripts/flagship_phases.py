"""Per-epoch phase breakdown of the flagship hogwild CIFAR config (VERDICT r3 #1).

Two passes over the exact workload `parity.py`'s cifar10_resnet18_hogwild
runs (synthetic CIFAR, ResNet-18 w64 bf16, batch 512, 10k-row validation):

1. ``--phases``: AsyncTrainer.profile_phases forces device results at
   phase boundaries (reshuffle / pull / train / push / fire_snapshot /
   fire_val / fire_callbacks) and prints mean seconds per phase per epoch,
   warmup epoch excluded. Forcing serializes the dispatch pipeline, so
   the per-phase numbers are costs, not a throughput measurement.
2. throughput: a plain fit with an epoch-timestamp callback — the same
   steady-state samples/sec `parity.py` reports.

Usage:  python scripts/flagship_phases.py [--epochs 6] [--quickish]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def build(quickish: bool):
    from elephas_tpu import compile_model
    from elephas_tpu.data.datasets import load_cifar10, one_hot
    from elephas_tpu.data.rdd import ShardedDataset
    from elephas_tpu.models import get_model

    (xtr, ytr), (xte, yte), real = load_cifar10()
    if quickish:
        xtr, ytr = xtr[:8192], ytr[:8192]
        xte, yte = xte[:2048], yte[:2048]
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32) * 255.0
    std = np.array([0.247, 0.243, 0.261], np.float32) * 255.0
    x = (xtr.astype(np.float32) - mean) / std
    y = one_hot(ytr, 10)
    xv = (xte.astype(np.float32) - mean) / std
    yv = one_hot(yte, 10)
    dtype = "bfloat16" if jax.default_backend() == "tpu" else "float32"
    net = compile_model(
        get_model("resnet18", num_classes=10, width=64, dtype=dtype),
        optimizer={"name": "momentum", "learning_rate": 0.05},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=x.shape[1:],
    )
    return net, ShardedDataset(x, y, 1), (xv, yv), len(x)


def make_trainer(net):
    from elephas_tpu.engine.async_engine import AsyncTrainer
    from elephas_tpu.parallel.mesh import build_mesh

    return AsyncTrainer(net, build_mesh(num_data=1), frequency="epoch", lock=False)


def run_phases(epochs: int, quickish: bool) -> dict:
    net, dataset, val, n_rows = build(quickish)
    trainer = make_trainer(net)
    trainer.profile_phases = True
    timer_times = []
    trainer.fit(
        dataset, epochs=epochs, batch_size=512, validation_data=val,
        callbacks=[lambda e, s, m: timer_times.append(time.perf_counter())],
    )
    # Warmup epoch (jit compile) excluded from every phase mean.
    table = {
        phase: round(float(np.mean(ts[1:])), 4) if len(ts) > 1 else None
        for phase, ts in sorted(trainer.phase_times.items())
    }
    worker = sum(v or 0 for k, v in table.items() if not k.startswith("fire_"))
    fire = sum(v or 0 for k, v in table.items() if k.startswith("fire_"))
    return {
        "phase_means_sec": table,
        "worker_critical_path_sec": round(worker, 4),
        "fire_offloaded_sec": round(fire, 4),
        "train_rows": n_rows,
    }


def run_throughput(epochs: int, quickish: bool) -> dict:
    net, dataset, val, n_rows = build(quickish)
    trainer = make_trainer(net)
    trainer.fit(
        dataset, epochs=epochs, batch_size=512, validation_data=val,
        callbacks=[lambda e, s, m: None],
    )
    # Worker-barrier timestamps: the true training cadence (fire-callback
    # times lag by the in-flight overlapped fire).
    times = trainer.epoch_end_times
    span = times[-1] - times[0]
    return {
        "samples_per_sec_steady": round(n_rows * (len(times) - 1) / span, 1),
        "epochs_timed": len(times) - 1,
        "train_rows": n_rows,
    }


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=6)
    parser.add_argument("--quickish", action="store_true",
                        help="8k-row slice (fast sanity, not the headline)")
    parser.add_argument("--phases-only", action="store_true")
    parser.add_argument("--throughput-only", action="store_true")
    args = parser.parse_args()

    out = {}
    if not args.throughput_only:
        out["phases"] = run_phases(args.epochs, args.quickish)
    if not args.phases_only:
        out["throughput"] = run_throughput(args.epochs, args.quickish)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
