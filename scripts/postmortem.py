#!/usr/bin/env python
"""postmortem: rebuild an incident timeline from on-disk telemetry only.

Every process that mounts a durable telemetry store (``obs.store``)
journals its flight notes, alert transitions, sampler ticks, and span
summaries as they happen. This CLI is the consumer for the case those
processes are ALL gone — point it at the root the stores were mounted
under (typically the chaos run's ``wal_root``) and it:

- discovers every store directory under the root (``obs.store_dirs``),
- clock-aligns the per-process journals (median wall-minus-mono base
  per boot — the ``trace_report.merge_dumps`` clockSync idea, smoothed
  against wall-clock steps),
- correlates flight events, alert transitions, lifecycle marks, and
  near-trigger metric excerpts into one causally-ordered timeline,
  stitching warm restarts (same store directory, new boot id) into a
  single per-process story,
- names the triggering event (earliest error-severity entry) and prints
  a replay-stable incident digest — rebuild the same journals twice and
  the digest is identical, which is what the chaos bench pins.

Usage:
    python scripts/postmortem.py /path/to/wal_root
    python scripts/postmortem.py /path/to/wal_root --out incident.md
    python scripts/postmortem.py /path/to/wal_root --json incident.json

Exit status is non-zero when no telemetry stores are found under the
root — an empty post-mortem is a finding, not a report.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elephas_tpu.obs.incident import (  # noqa: E402
    IncidentBuilder,
    render_markdown,
)


def build_incident(root: str, metric_window_s: float = 2.0,
                   title: str = "Incident report") -> Optional[dict]:
    """Discover + build; None when the root holds no stores."""
    builder = IncidentBuilder()
    if not builder.discover(root):
        return None
    incident = builder.build(metric_window_s=metric_window_s)
    incident["markdown"] = render_markdown(incident, title=title)
    return incident


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Rebuild an incident bundle from on-disk telemetry "
                    "stores (no live process required)")
    ap.add_argument("root",
                    help="directory tree the stores were mounted under "
                         "(e.g. the chaos run's wal_root)")
    ap.add_argument("--out", default=None,
                    help="write the markdown timeline here "
                         "(default: print to stdout)")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="also write the full incident bundle as JSON")
    ap.add_argument("--metric-window", type=float, default=2.0,
                    help="seconds of metric ticks to keep around the "
                         "triggering event (default 2.0)")
    args = ap.parse_args(argv)

    incident = build_incident(args.root,
                              metric_window_s=args.metric_window)
    if incident is None:
        print(f"postmortem: no telemetry stores under {args.root}",
              file=sys.stderr)
        return 1

    markdown = incident.pop("markdown")
    if args.json_out:
        with open(args.json_out, "w") as fh:
            json.dump(incident, fh, indent=1, sort_keys=True)
        print(f"wrote {args.json_out}")
    if args.out:
        with open(args.out, "w") as fh:
            fh.write(markdown)
        print(f"wrote {args.out}")
    else:
        print(markdown)

    trigger = incident.get("triggering_event")
    kind = trigger["kind"] if trigger else "(none)"
    print(f"\ndigest: {incident['digest']}  triggering event: {kind}  "
          f"stores: {incident['stores']}  "
          f"timeline entries: {len(incident['timeline'])}",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
