"""Chaos bench: measured recovery behavior of the resilience layer.

Runs a small elastic async fit (socket PS transport, WAL-backed) under
three fault scenarios plus an undisturbed baseline, and emits one JSON
object per scenario so the numbers land as a committed artifact
(``--out BENCH_CHAOS.json``):

- ``{"scenario": "baseline"}`` — undisturbed elastic fit; its
  ``final_loss`` is the tolerance anchor for every chaos arm (same data,
  same seeds, unit-keyed determinism).
- ``{"scenario": "kill_ps"}`` — the parameter server is crashed
  (``SocketServer.kill``: acceptor down, live connections severed, NO
  clean WAL sync) once a few updates are durable, held down for
  ``--outage`` seconds, then warm-restarted on the same port from the
  same WAL dir. Reports worker-observed MTTR samples (outage start →
  first successful reconnect), units re-queued, and the durable version
  the restart resumed from.
- ``{"scenario": "kill_worker"}`` — a ``FaultPlan`` kills one worker
  thread at its second leased unit; the monitor re-queues its pending
  unit to survivors. Reports the re-queue count and the exact
  frequency-unit accounting.
- ``{"scenario": "partition"}`` — a deterministic partition window
  drops every wire frame with ``start <= seq < end``; clients ride
  their retry machinery through it. Reports retry-visible effects and
  the plan's ``trace_digest`` (replays from the same seed match it).

MTTR here is end-to-end as a WORKER experiences it: from the first
failed round trip to the first successful one after recovery — it
includes the bench's own outage hold-down, the client retry backoff,
and reconnect cost, which is the number an operator actually sees.

``--trace`` runs the whole bench under the obs tracer and emits the
distributed-trace artifacts: the in-process ring is split into
per-role dumps (``chaos_trace_worker.json`` — trainer lanes, client
``ps/pull``/``ps/push``, comms queue waits — and ``chaos_trace_ps.json``
— the PS-side ``ps/handle_*``/``ps/apply`` spans, exactly what a remote
PS's ``/trace`` route would have served), then merges them through
``scripts/trace_report.py --merge`` into ``chaos_trace_merged.json``
and prints the per-unit queue/wire/lock/train critical-path table.
Because the wire codec propagates ``(trace_id, span_id)``, the worker
and PS dumps join on trace id exactly as true multi-process dumps do.

``--health`` appends a ``{"scenario": "health"}`` row: a seeded
kill-worker fit measured through the PS's ``StalenessLedger`` (the
per-worker contribution table, exact lag percentiles, bucketed lag
histogram) plus the deterministic fake-clock ``alert_ladder`` sequence
— same ``--seed`` → same ordered alert kinds, pinned by
``test_chaos.py`` and gated by ``bench_gate.py``'s ``staleness_p95``
rule.

``--shards`` appends a ``{"scenario": "shard_kill"}`` row: a K=2
``ShardGroup`` with one warm standby per shard takes a seeded push
sequence, shard 0's primary is crashed (``kill``: no clean WAL sync),
and the group monitor promotes the WAL-streamed spare. The row commits
the measured ``shard_failover_mttr_s`` (wall seconds from kill to the
first successful pull through the re-resolved client — detection +
promotion + client re-dial, the number an operator sees),
``acked_state_recovered`` (the post-promotion pull is digest-identical
to the last acked state: zero acked-update loss), and the replay-stable
``final_digest`` (same seed → same digest on every run; a replay that
drifts changed the data path). Both gated by ``bench_gate.py``
(``shard_failover_mttr_s`` ceiling, ``acked_state_recovered`` equal).

``--postmortem`` appends a ``{"scenario": "postmortem"}`` row: the
shard-kill arc run with durable telemetry stores mounted next to every
member's WAL, then EVERY process hard-killed and the incident rebuilt
from the on-disk journals alone (``obs.incident``). Commits the
replay-stable incident digest, the triggering event the reconstruction
names (the shard kill), and the push-path persistence overhead of the
mounted store — all gated.

``--staleness`` appends a ``{"scenario": "staleness"}`` row: a fully
deterministic convergence-vs-staleness sweep over the wire admission
path — the same seeded fast/slow-worker schedule run against
``max_staleness ∈ {∞, 8, 2}`` (a table of final loss + per-worker
applied/damped/rejected counts from the PS's own ledger, replay-stable
digest), plus the client-side AIMD sync-interval ratchet trajectory
(4 → 2 → 1 under forced rejections, +0.25/accept recovery). Gated by
``bench_gate.py``: ``staleness_rejected_nonzero`` (the hard bound must
have refused deltas), the ``staleness_recovery_gain`` floor (bounded
admission never converges worse than unbounded), and the digest.

``--fleet`` appends a ``{"scenario": "fleet"}`` row: the kill_ps chaos
arm re-run with ops endpoints mounted on BOTH sides (the elastic PS via
``ps_ops_port``, the trainer process via ``mount_ops``) and a
``FleetAggregator`` polling them through the outage. The row commits
the PS roster entry's observed transition sequence — a warm-restarted
PS must read ``alive → stale → dead → alive`` in the fleet view, never
vanish — plus the measured per-poll scrape cost and merge cost that
``bench_gate.py``'s absolute ``fleet_scrape_ms_mean`` /
``fleet_merge_ms_mean`` ceilings gate.

Importable without a TPU; tier-1-sized defaults finish in ~1 min on
CPU. Usage:
    python scripts/chaos_bench.py [--epochs 4] [--outage 4.0]
        [--n 256] [--out BENCH_CHAOS.json] [--health] [--seed 11]
        [--trace] [--trace-dir D] [--fleet]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_blobs(n: int, dim: int = 8, classes: int = 3, seed: int = 3):
    """Gaussian class blobs + one-hot labels (mirrors the test fixture —
    re-implemented here so the bench doesn't import from tests/)."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, dim)) * 3.0
    labels = rng.integers(0, classes, size=n)
    x = centers[labels] + rng.standard_normal((n, dim))
    y = np.eye(classes, dtype=np.float32)[labels]
    return x.astype(np.float32), y


def _build_net():
    from elephas_tpu import compile_model
    from elephas_tpu.models import get_model

    return compile_model(
        get_model("mlp", features=(16,), num_classes=3),
        optimizer={"name": "sgd", "learning_rate": 0.05},
        loss="categorical_crossentropy", metrics=["acc"],
        input_shape=(8,), seed=0,
    )


def _build_trainer(fault_plan=None, wal_dir=None, grace: float = 30.0,
                   ps_ops_port=None):
    from elephas_tpu.engine.async_engine import AsyncTrainer
    from elephas_tpu.parallel.mesh import build_mesh

    net = _build_net()
    return AsyncTrainer(
        net, build_mesh(num_data=2), frequency="epoch",
        parameter_server_mode="socket", port=0, elastic=True,
        fault_plan=fault_plan, ps_wal_dir=wal_dir, ps_recovery_grace=grace,
        ps_ops_port=ps_ops_port,
    )


def _run_fit(trainer, x, y, epochs: int, chaos=None):
    """Fit on a worker thread (chaos needs the main thread free to kill
    things); returns (history, stats, wall_seconds, chaos_detail)."""
    from elephas_tpu.data.rdd import ShardedDataset

    result, detail = {}, {}

    def run():
        result["out"] = trainer.fit(ShardedDataset(x, y, 2),
                                    epochs=epochs, batch_size=16)

    t0 = time.perf_counter()
    th = threading.Thread(target=run)
    th.start()
    if chaos is not None:
        detail = chaos(trainer)
    th.join()
    wall = time.perf_counter() - t0
    _, history = result["out"]
    return history, trainer.elastic_stats, wall, detail


def _stats_row(scenario, history, stats, wall, **extra):
    mttr = stats["mttr_samples"]
    return {
        "scenario": scenario,
        "wall_s": round(wall, 2),
        "final_loss": round(float(history["loss"][-1]), 5),
        "completed_units": stats["completed_units"],
        "requeued_units": stats["requeued_units"],
        "worker_deaths": len(stats["worker_deaths"]),
        "ps_outages": len(stats["ps_outages"]),
        "mttr_mean_s": round(sum(mttr) / len(mttr), 3) if mttr else None,
        "mttr_max_s": round(max(mttr), 3) if mttr else None,
        **extra,
    }


def scenario_baseline(x, y, epochs):
    history, stats, wall, _ = _run_fit(_build_trainer(), x, y, epochs)
    return _stats_row("baseline", history, stats, wall)


def scenario_kill_ps(x, y, epochs, outage: float):
    from elephas_tpu.parameter.server import make_server

    def chaos(trainer):
        while trainer._elastic_server is None:
            time.sleep(0.005)
        server = trainer._elastic_server
        port, wal_dir = server.port, trainer.ps_wal_dir
        while server.buffer.version < 3:  # let some updates become durable
            time.sleep(0.005)
        server.kill()
        killed_at = server.buffer.version
        time.sleep(outage)  # outage > client retry budget → real failures
        # Warm restart on the same port: a COLD initial store (as a real
        # supervisor restart would have), immediately superseded by the
        # WAL's newest durable snapshot during construction.
        cold = _build_net()
        fresh = make_server(
            "socket",
            {"params": cold.params, "batch_stats": cold.batch_stats},
            port=port, wal_dir=wal_dir,
        )
        fresh.start()
        trainer._elastic_server = fresh
        return {"durable_version_at_kill": killed_at,
                "resumed_version": fresh.buffer.version,
                "outage_hold_s": outage}

    with tempfile.TemporaryDirectory() as wal_dir:
        trainer = _build_trainer(wal_dir=wal_dir, grace=max(30.0, 4 * outage))
        history, stats, wall, detail = _run_fit(trainer, x, y, epochs,
                                                chaos=chaos)
    return _stats_row("kill_ps", history, stats, wall, **detail)


def scenario_kill_worker(x, y, epochs):
    from elephas_tpu.resilience import FaultPlan

    plan = FaultPlan(seed=11, kill_worker_at={"w1": 1})
    trainer = _build_trainer(fault_plan=plan)
    history, stats, wall, _ = _run_fit(trainer, x, y, epochs)
    return _stats_row("kill_worker", history, stats, wall,
                      trace_digest=hex(plan.trace_digest()))


def scenario_partition(x, y, epochs):
    from elephas_tpu.resilience import FaultPlan

    # Frames 6..14 (per peer, send side) hit the void: mid-fit both
    # workers lose a handful of round trips and retry through them.
    plan = FaultPlan(seed=23, partition={"*": (6, 14)})
    trainer = _build_trainer(fault_plan=plan)
    history, stats, wall, _ = _run_fit(trainer, x, y, epochs)
    return _stats_row("partition", history, stats, wall,
                      trace_digest=hex(plan.trace_digest()))


def scenario_fleet(x, y, epochs, outage: float):
    """``--fleet``: the kill_ps arm observed through the federation
    plane. The elastic PS mounts an ops endpoint (``ps_ops_port=0``),
    the trainer process mounts its own (role ``worker``), and a
    ``FleetAggregator`` polls both at a 0.25 s cadence through kill →
    outage → warm restart. The PS roster entry must walk
    alive → stale → dead → alive — dead, not gone, is the contract.
    Per-poll scrape and merge costs are measured for the gate."""
    from elephas_tpu.obs.fleet import FleetAggregator
    from elephas_tpu.parameter.server import make_server

    dead_after = max(0.75, min(2.0, outage / 2.0))
    agg = FleetAggregator(dead_after=dead_after, timeout=1.0)
    scrape_ms, merge_ms = [], []
    stop = threading.Event()

    def poller():
        while not stop.is_set():
            t0 = time.perf_counter()
            agg.poll()
            scrape_ms.append((time.perf_counter() - t0) * 1000.0)
            t0 = time.perf_counter()
            agg.snapshot()
            merge_ms.append((time.perf_counter() - t0) * 1000.0)
            stop.wait(0.25)

    poll_thread = threading.Thread(target=poller, daemon=True)

    def chaos(trainer):
        while (trainer._elastic_server is None
               or trainer._elastic_server.ops is None):
            time.sleep(0.005)
        server = trainer._elastic_server
        port, wal_dir = server.port, trainer.ps_wal_dir
        ops_port = server.ops.port  # warm restart re-mounts HERE, so
        agg.add(server.ops.url, name="ps")  # the roster URL stays valid
        agg.add(trainer.mount_ops().url, name="worker")
        poll_thread.start()
        while server.buffer.version < 3:
            time.sleep(0.005)
        server.kill()  # also unmounts ops: the fleet MUST see it go dark
        killed_at = server.buffer.version
        time.sleep(outage)
        cold = _build_net()
        fresh = make_server(
            "socket",
            {"params": cold.params, "batch_stats": cold.batch_stats},
            port=port, wal_dir=wal_dir, ops_port=ops_port,
        )
        fresh.start()
        trainer._elastic_server = fresh
        # Hold until the poller has seen the restarted PS: the
        # alive-after-outage transition must be recorded while the
        # server is still up (the fit teardown stops it at the end).
        deadline = time.perf_counter() + 15.0
        while (agg.registry.get("ps").status != "alive"
               and time.perf_counter() < deadline):
            time.sleep(0.05)
        # Polling ends HERE, not in the finally: once the restart has
        # been observed the transition record is complete, and letting
        # the poller race the fit teardown would append a spurious
        # trailing "stale" when it catches the final server stop.
        stop.set()
        return {"durable_version_at_kill": killed_at,
                "resumed_version": fresh.buffer.version,
                "outage_hold_s": outage}

    with tempfile.TemporaryDirectory() as wal_dir:
        trainer = _build_trainer(wal_dir=wal_dir, grace=max(30.0, 4 * outage),
                                 ps_ops_port=0)
        try:
            history, stats, wall, detail = _run_fit(trainer, x, y, epochs,
                                                    chaos=chaos)
        finally:
            stop.set()
            if poll_thread.is_alive():
                poll_thread.join(timeout=5)
            trainer.unmount_ops()

    ps_entry = agg.registry.get("ps")
    seq = [s for _, s in ps_entry.transitions]
    saw_outage = ("alive" in seq and "dead" in seq
                  and seq.index("alive") < seq.index("dead")
                  and seq[-1] == "alive")
    worker_seq = [s for _, s in agg.registry.get("worker").transitions]
    row = _stats_row(
        "fleet", history, stats, wall, **detail,
        fleet_transitions=seq,
        fleet_saw_outage=saw_outage,
        worker_transitions=worker_seq,
        dead_after_s=dead_after,
        fleet_polls=agg.polls,
        fleet_scrape_ms_mean=round(sum(scrape_ms) / len(scrape_ms), 2),
        fleet_scrape_ms_max=round(max(scrape_ms), 2),
        fleet_merge_ms_mean=round(sum(merge_ms) / len(merge_ms), 2),
    )
    # The fleet arm races the (fast) fit against the kill, so the final
    # evaluation may run against the cold-restarted store — its loss is
    # timing noise, not a gated signal. Dropping it keeps the committed
    # baseline row from teaching bench_gate a nondeterministic rule.
    del row["final_loss"]
    return row


def alert_ladder(seed: int):
    """Deterministic alert replay: drive a PRIVATE registry/flight/
    engine stack (injected clock, seeded lag draws) through a staleness
    ramp, a straggler burst, and an expiry-counter burn, and return the
    ordered kinds that fired. Same seed → byte-identical sequence —
    ``test_chaos.py`` pins it, and the ``--health`` row commits it.

    The ladder exercises every evaluation mode the stock pack uses:
    value rules on labeled histogram percentiles (per-worker matching),
    and a windowed rate rule with ``burn=2`` (two consecutive trips
    before it fires)."""
    from elephas_tpu import obs
    from elephas_tpu.obs.health import record_staleness

    reg = obs.MetricsRegistry()
    engine = obs.AlertEngine(registry=reg, flight=obs.FlightRecorder(),
                             clock=lambda: 0.0)
    rng = np.random.default_rng(seed)
    # t=0: healthy lags on w0 — nothing fires.
    for lag in rng.integers(0, 3, size=32):
        record_staleness(None, "w0", int(lag), registry=reg)
    engine.evaluate(now=0.0)
    # t=10: w0's p95 ramps past 8 → staleness_spike.
    for lag in rng.integers(10, 14, size=64):
        record_staleness(None, "w0", int(lag), registry=reg)
    engine.evaluate(now=10.0)
    # t=20: w1 appears far behind the fleet (>32) → its key trips BOTH
    # staleness rules, in rule-pack order: staleness_spike, then
    # worker_lagging.
    for lag in rng.integers(40, 48, size=64):
        record_staleness(None, "w1", int(lag), registry=reg)
    engine.evaluate(now=20.0)
    # t=30..50: expiry-counter burst at ~3/s (rule threshold 0.1/s,
    # burn=2): first rated point trips at t=40, fires at t=50.
    expired = reg.counter("ps_worker_expired_total",
                          help="probe counter for the alert ladder")
    engine.evaluate(now=30.0)
    expired.inc(30)
    engine.evaluate(now=40.0)
    expired.inc(30)
    engine.evaluate(now=50.0)
    return [a["kind"] for a in engine.fired]


def goodput_burn_ladder(seed: int):
    """Deterministic multi-window burn-rate replay: drive a PRIVATE
    GoodputLedger/registry/alert-engine stack through good traffic, a
    bad-TTFT burst, a recovery, and a second burst — all on pinned
    timestamps — and return the ordered rule names that fired. Same
    seed → byte-identical sequence; ``test_chaos.py`` pins it and the
    ``--health`` row commits it.

    The shape under test: the ``serving_goodput_burn`` gauge is
    ``min(fast, slow bad fraction) / budget``, so the burst must poison
    BOTH windows to fire (fast+slow AND-gate), the warn rule
    (``goodput_burn_high``, burn > 1) precedes the page rule
    (``goodput_burn_critical``, burn > 6) in pack order, both latch
    until the fast window runs clean, and the second burst re-fires
    them — latch-until-clean, not fire-once."""
    from types import SimpleNamespace

    from elephas_tpu import obs

    reg = obs.MetricsRegistry()
    engine = obs.AlertEngine(registry=reg, flight=obs.FlightRecorder(),
                             clock=lambda: 0.0)
    ledger = obs.GoodputLedger(clock=lambda: 0.0, registry=reg)
    rng = np.random.default_rng(seed)

    def finish(t, ttft):
        ledger.record(SimpleNamespace(
            status="completed", ttft_s=ttft,
            itl_s_avg=float(rng.uniform(0.001, 0.01))), now=t)

    # t=0..40: healthy traffic — every objective met, burn 0.
    for t in np.linspace(0.0, 40.0, 40):
        finish(float(t), ttft=float(rng.uniform(0.01, 0.1)))
    engine.evaluate(now=41.0)
    # t=45..55: TTFT burst (5 s >> the 2.5 s objective). 30 bad against
    # 40 good poisons the fast window (~43% bad) AND the slow window
    # (~43% too — everything is inside 600 s), so burn >> 6: the warn
    # fires, then the page, in pack order.
    for t in np.linspace(45.0, 55.0, 30):
        finish(float(t), ttft=5.0)
    engine.evaluate(now=56.0)
    # t=70..130: recovery traffic. By t=130 the fast window (last 60 s)
    # holds only good finishes → fast bad fraction 0 → burn 0: both
    # rules run clean and re-arm.
    for t in np.linspace(70.0, 130.0, 60):
        finish(float(t), ttft=float(rng.uniform(0.01, 0.1)))
    engine.evaluate(now=131.0)
    # t=135..145: second burst — the re-armed ladder fires again.
    for t in np.linspace(135.0, 145.0, 30):
        finish(float(t), ttft=5.0)
    engine.evaluate(now=146.0)
    return [a["rule"] for a in engine.fired]


def staleness_probe(seed: int, steps: int = 24):
    """Deterministic wire-level staleness ladder against a real socket
    PS: per step, a probe client pulls (pinning the version it "trained
    against"), a feeder client advances the server a seeded number of
    versions with re-pulled zero deltas, then the probe pushes — so the
    probe's applied lag is EXACT by construction. The ledger's wire-side
    measurement is asserted equal to the constructed ladder, which is
    the end-to-end proof the ``sv`` stamp survives encode→socket→apply.

    Returns ``(lags, probe_row)``: the seeded lag list (the measured
    distribution) and the probe's ledger row."""
    import jax

    from elephas_tpu.parameter.client import make_client
    from elephas_tpu.parameter.server import make_server

    net = _build_net()
    store = {"params": net.params, "batch_stats": net.batch_stats}
    zero = jax.tree_util.tree_map(
        lambda a: np.zeros_like(np.asarray(a)), jax.device_get(store))
    server = make_server("socket", store, port=0)
    server.start()
    try:
        addr = f"127.0.0.1:{server.port}"
        probe = make_client("socket", addr)
        probe.worker_id = "probe"
        feeder = make_client("socket", addr)
        feeder.worker_id = "feeder"
        lags = [int(v) for v in
                np.random.default_rng(seed).integers(0, 12, size=steps)]
        for lag in lags:
            probe.get_parameters()
            for _ in range(lag):
                # Re-pull before each feeder push so the feeder itself
                # contributes lag-0 samples, not a growing tail.
                feeder.get_parameters()
                feeder.update_parameters(zero)
            probe.update_parameters(zero)
        row = server.ledger.snapshot()["workers"]["probe"]
        assert row["updates"] == steps, row
        assert row["lag_sum"] == sum(lags), (row, lags)
        probe.close()
        feeder.close()
        return lags, row
    finally:
        server.stop()


def scenario_health(x, y, epochs, seed: int = 11):
    """Training-health probe (``--health``): a seeded kill-worker chaos
    fit measured through the PS's staleness ledger (the per-worker
    contribution table), the deterministic ``staleness_probe`` ladder
    (exact wire-measured lag distribution — the gated ``staleness_p95``),
    and the ``alert_ladder`` sequence for the same seed."""
    from elephas_tpu.obs.health import STALENESS_BUCKETS
    from elephas_tpu.resilience import FaultPlan

    plan = FaultPlan(seed=seed, kill_worker_at={"w1": 1})
    trainer = _build_trainer(fault_plan=plan)
    captured = {}

    def chaos(trainer):
        # Only capture the live server: its ledger outlives the fit's
        # teardown, so the table below is read after join, race-free.
        while trainer._elastic_server is None:
            time.sleep(0.005)
        captured["ledger"] = trainer._elastic_server.ledger
        return {}

    history, stats, wall, _ = _run_fit(trainer, x, y, epochs, chaos=chaos)
    led = captured["ledger"].snapshot()
    workers = {
        w: {k: row[k] for k in ("updates", "lag_mean", "lag_max", "bytes")}
        for w, row in sorted(led["workers"].items())
    }
    lags, probe_row = staleness_probe(seed)
    arr = np.asarray(lags)
    hist, lo = {}, -1
    for bound in STALENESS_BUCKETS:
        hist[f"le_{bound}"] = int(((arr > lo) & (arr <= bound)).sum())
        lo = bound
    hist[f"gt_{STALENESS_BUCKETS[-1]}"] = int((arr > lo).sum())
    return _stats_row(
        "health", history, stats, wall,
        seed=seed,
        staleness_p50=round(float(np.percentile(arr, 50)), 3),
        staleness_p95=round(float(np.percentile(arr, 95)), 3),
        staleness_p99=round(float(np.percentile(arr, 99)), 3),
        staleness_hist=hist,
        probe_updates=probe_row["updates"],
        probe_lag_max=probe_row["lag_max"],
        fit_staleness_p95=led["lag_p95"],
        unstamped_updates=led["unstamped_updates"],
        workers=workers,
        alert_seq=alert_ladder(seed),
        burn_alert_seq=goodput_burn_ladder(seed),
    )


def scenario_shard_kill(seed: int = 11, k: int = 2, updates: int = 6):
    """``--shards``: kill a shard primary under a seeded push sequence
    and measure the standby promotion end to end. Runs the ShardGroup
    directly (no training loop): the seeded deltas make the final tree
    — and therefore ``final_digest`` — bit-replayable, so the committed
    digest doubles as a data-path regression check."""
    import hashlib

    import jax

    from elephas_tpu.obs.canary import PSCanary
    from elephas_tpu.parameter.group import ShardGroup

    def digest(tree):
        h = hashlib.sha256()
        leaves = jax.tree_util.tree_flatten_with_path(tree)[0]
        for path, leaf in sorted(leaves, key=lambda kv: str(kv[0])):
            h.update(str(path).encode())
            h.update(np.ascontiguousarray(leaf).tobytes())
        return h.hexdigest()[:16]

    net = _build_net()
    store = jax.device_get({"params": net.params,
                            "batch_stats": net.batch_stats})
    rng = np.random.default_rng(seed)

    with tempfile.TemporaryDirectory() as wal_root:
        group = ShardGroup(store, k, mode="socket", standby=1,
                           wal_root=wal_root, suspect_after=0.3)
        group.start()
        client = group.client()
        try:
            for _ in range(updates):
                delta = jax.tree_util.tree_map(
                    lambda a: rng.normal(
                        scale=0.01, size=np.shape(a)
                    ).astype(np.asarray(a).dtype), store)
                client.update_parameters(delta)
            acked = client.get_parameters()
            acked_digest = digest(acked)
            # Spares must be caught up before the kill — the WAL made
            # every acked update durable (wal_every=1), the streamer
            # just needs to have applied it.
            deadline = time.perf_counter() + 10.0
            while any(group.streamer_of(i).lag()
                      for i in range(k)) and time.perf_counter() < deadline:
                time.sleep(0.01)

            # Blackbox canary on its OWN client — the probe must see the
            # outage through the same re-resolve/retry path a real
            # worker uses, without sharing the measured client's
            # connection state.
            probe_client = group.client()
            probe_client.worker_id = "canary"
            canary = PSCanary(probe_client, group=group)
            pre = canary.probe()
            standby_lag_prekill = pre["standby_lag"]

            group.start_monitor(interval=0.05)
            t0 = time.perf_counter()
            group.kill_primary(0)
            # The canary probes from its own thread so the MTTR loop
            # below stays exactly what it measures: the canary's failed
            # round-trips each burn the client retry budget, and running
            # them inline would bill that to the failover.
            probe_log = []  # (seconds since kill, probe ok)
            stop_probing = threading.Event()

            def probe_loop():
                while not stop_probing.is_set():
                    p = canary.probe()
                    probe_log.append((time.perf_counter() - t0,
                                      bool(p["ok"])))
                    stop_probing.wait(0.05)

            prober = threading.Thread(target=probe_loop, daemon=True)
            prober.start()
            after = None
            while after is None and time.perf_counter() - t0 < 60.0:
                try:
                    after = client.get_parameters()
                except Exception:
                    time.sleep(0.02)
            mttr = time.perf_counter() - t0
            stop_probing.set()
            prober.join(timeout=30.0)
            # One probe after recovery so the log always ends healthy
            # when the failover worked.
            p = canary.probe()
            probe_log.append((time.perf_counter() - t0, bool(p["ok"])))
            # Canary-visible outage window: first failed probe to the
            # first success after it.
            first_fail = next((t for t, ok in probe_log if not ok), None)
            outage_s = None
            if first_fail is not None:
                outage_end = next((t for t, ok in probe_log
                                   if t > first_fail and ok), None)
                if outage_end is not None:
                    outage_s = outage_end - first_fail
            csnap = canary.snapshot()
            promo = group.promotions[-1] if group.promotions else {}
            row = {
                "scenario": "shard_kill", "shards": k, "standby": 1,
                "updates_acked": updates,
                "shard_failover_mttr_s": round(mttr, 3),
                "promote_s": round(promo.get("promote_s", -1.0), 4),
                "caught_up_version": promo.get("caught_up_version"),
                "old_boot_fenced": group.directory.is_fenced(
                    promo.get("old_boot")),
                "acked_state_recovered": (after is not None
                                          and digest(after) == acked_digest),
                "final_digest": acked_digest,
                "canary_probes": csnap["probes"],
                "canary_failed_probes": csnap["failures"],
                "canary_outage_s": (None if outage_s is None
                                    else round(outage_s, 3)),
                # bench_gate pins this to True ("equal" check): the
                # blackbox probe must have SEEN the kill and seen it
                # end.
                "canary_saw_outage": (first_fail is not None
                                      and outage_s is not None),
                "standby_lag_prekill": standby_lag_prekill,
                "seed": seed,
            }
            probe_client.close()
            return row
        finally:
            client.close()
            group.stop()


def _store_push_overhead(seed: int = 11, updates: int = 40,
                         rounds: int = 3, attempts: int = 3):
    """Persistence overhead on the PS push path: seeded update loops
    against two otherwise-identical servers — telemetry store mounted
    vs disabled — alternating order, best-of-rounds, retried when the
    measurement lands noisy (the ``lm_bench`` trace/canary overhead
    methodology). No WAL on either side: WAL fsyncs dominate the push
    wall and are identical noise in both arms — this isolates the
    store mount's marginal cost on the path that must not pay one (the
    store is off the hot path by design: pushes journal nothing; only
    anomalies, alert transitions, and sampler ticks do)."""
    import jax

    from elephas_tpu.parameter.server import SocketServer

    net = _build_net()
    store0 = jax.device_get({"params": net.params,
                             "batch_stats": net.batch_stats})
    rng = np.random.default_rng(seed)
    deltas = [jax.tree_util.tree_map(
        lambda a: rng.normal(scale=0.01, size=np.shape(a))
        .astype(np.asarray(a).dtype), store0) for _ in range(updates)]

    with tempfile.TemporaryDirectory() as tmp:
        pairs = []  # (server, client) — [0] store on, [1] store off
        for store_on in (True, False):
            srv = SocketServer(
                store0, port=0,
                store_dir=os.path.join(tmp, "telemetry") if store_on
                else None)
            srv.start()
            pairs.append((srv, srv.client()))
        try:
            def window(client) -> float:
                t0 = time.perf_counter()
                for delta in deltas:
                    client.update_parameters(delta)
                return updates / (time.perf_counter() - t0)

            for _, client in pairs:  # connection + codec warmup
                for delta in deltas[:5]:
                    client.update_parameters(delta)
            overhead = None
            for _ in range(attempts):
                on, off = [], []
                for _ in range(rounds):
                    on.append(window(pairs[0][1]))
                    off.append(window(pairs[1][1]))
                    off.append(window(pairs[1][1]))
                    on.append(window(pairs[0][1]))
                overhead = 1.0 - max(on) / max(off)
                if overhead < 0.02:
                    break
        finally:
            for srv, client in pairs:
                client.close()
                srv.stop()
    return round(100.0 * overhead, 3)


def scenario_postmortem(seed: int = 11, k: int = 2, updates: int = 6):
    """``--postmortem``: the durable-telemetry acid test. Runs a
    deterministic shard-kill arc with telemetry stores mounted next to
    every member's WAL, hard-kills EVERY process (kill semantics — no
    clean shutdown anywhere), then reconstructs the incident purely
    from the on-disk journals with ``obs.incident.IncidentBuilder``
    (what ``scripts/postmortem.py`` runs). The rebuilt timeline must
    name the shard kill as the triggering event, and the incident
    digest — a set digest over journaled event identities, immune to
    timing-dependent repetition — must replay bit-identically; it is
    pinned in BENCH_CHAOS.json and gated with an equal rule exactly
    like the data-path ``final_digest``. Promotion is driven directly
    (no monitor thread, no canary) so the journaled event SET is
    deterministic run to run."""
    import shutil

    import jax

    from elephas_tpu.obs.incident import IncidentBuilder
    from elephas_tpu.parameter.group import ShardGroup

    net = _build_net()
    store0 = jax.device_get({"params": net.params,
                             "batch_stats": net.batch_stats})
    rng = np.random.default_rng(seed)
    wal_root = tempfile.mkdtemp(prefix="chaos_postmortem_")
    group = None
    try:
        group = ShardGroup(store0, k, mode="socket", standby=1,
                           wal_root=wal_root, suspect_after=0.3)
        group.start()
        client = group.client()
        try:
            for _ in range(updates):
                delta = jax.tree_util.tree_map(
                    lambda a: rng.normal(
                        scale=0.01, size=np.shape(a)
                    ).astype(np.asarray(a).dtype), store0)
                client.update_parameters(delta)
        finally:
            client.close()
        deadline = time.perf_counter() + 10.0
        while any(group.streamer_of(i) is not None
                  and group.streamer_of(i).lag()
                  for i in range(k)) and time.perf_counter() < deadline:
            time.sleep(0.01)

        # The incident: shard 0's primary crashes mid-traffic, its warm
        # spare is promoted, then the WHOLE fleet is hard-killed — the
        # post-mortem must work with every process gone.
        group.kill_primary(0)
        promoted = group.promote(0)
        recovered = group.get_parameters() is not None
        for shard in range(k):
            group.kill_primary(shard)

        def rebuild():
            builder = IncidentBuilder()
            builder.discover(wal_root)
            return builder.build()

        incident = rebuild()
        replay = rebuild()
        trigger = incident.get("triggering_event") or {}
        corrupt = sum(p.get("corrupt_tails", 0)
                      for p in incident["processes"])
        row = {
            "scenario": "postmortem", "shards": k, "standby": 1,
            "updates_acked": updates,
            "promoted": bool(promoted),
            "recovered": bool(recovered),
            # Rebuilt from disk alone, after every member was killed.
            "postmortem_rebuilt": bool(incident["timeline"]),
            "stores_discovered": incident["stores"],
            "timeline_entries": len(incident["timeline"]),
            "journal_records": sum(p["records"]
                                   for p in incident["processes"]),
            "corrupt_tails": corrupt,
            "triggering_event": trigger.get("kind"),
            "trigger_proc": trigger.get("proc"),
            # bench_gate pins both ("equal"): the reconstruction must
            # blame the shard kill, on the shard that was killed.
            "trigger_is_shard_kill": (trigger.get("kind") == "ps_kill"
                                      and trigger.get("proc") == "shard0"),
            "incident_digest": incident["digest"],
            "digest_replay_stable": incident["digest"] == replay["digest"],
            "store_overhead_pct": _store_push_overhead(seed=seed),
            "seed": seed,
        }
        row["store_overhead_within_2pct"] = row["store_overhead_pct"] <= 2.0
        return row
    finally:
        if group is not None:
            group.stop()
        shutil.rmtree(wal_root, ignore_errors=True)


def scenario_staleness(seed: int = 11, steps: int = 60):
    """``--staleness``: convergence vs the admission bound, measured
    through the real socket wire path, fully deterministic (single
    thread, seeded — same seed → same sweep table and digest).

    The workload is a quadratic bowl (loss = ||w - w*||^2 / 2) pushed at
    by two workers: a FAST one that re-pulls every step (lag 0, its
    delta is the true gradient step), and a SLOW one that re-pulls only
    every ``refresh`` steps but pushes every step — so between refreshes
    it re-sends the gradient of an increasingly stale base, the classic
    stale-delta overshoot. The sweep runs the identical seeded schedule
    against ``max_staleness ∈ {∞, 8, 2}`` (soft bound at half the hard
    bound): unbounded admission lets every stale push land (worst final
    loss), damping decays them, and the hard bound rejects them outright
    — the convergence-vs-staleness table the bounded-staleness trade
    turns on. Rejected/damped counts come from the server's own
    StalenessLedger, so the row also proves the wire admission path
    end to end.

    The row additionally commits the client half of the loop: a
    ``_CommsPipeline`` with a units-per-push baseline of 4 driven
    against the max_staleness=2 server — three forced rejections halve
    its interval 4 → 2 → 1, then accepted pushes relax it +0.25 per
    accept (``sync_interval_path``, replay-stable)."""
    import hashlib

    from elephas_tpu.parameter.client import (
        StaleDeltaRejected, make_client,
    )
    from elephas_tpu.parameter.server import make_server

    dim, lr, refresh = 8, 0.12, 12
    rng = np.random.default_rng(seed)
    target = rng.standard_normal(dim).astype(np.float32)
    w0 = np.zeros(dim, np.float32)

    def run_arm(bound):
        soft = None if bound is None else max(1, bound // 2)
        server = make_server(
            "socket", {"params": {"w": w0.copy()}, "batch_stats": {}},
            port=0, max_staleness=bound, staleness_soft=soft,
        )
        server.start()
        try:
            addr = f"127.0.0.1:{server.port}"
            fast = make_client("socket", addr)
            fast.worker_id = "fast"
            slow = make_client("socket", addr)
            slow.worker_id = "slow"
            rejected = 0
            stale_base = None
            for step in range(steps):
                cur = np.asarray(fast.get_parameters()["params"]["w"])
                fast.update_parameters(
                    {"params": {"w": lr * (cur - target)},
                     "batch_stats": {}})
                if step % refresh == 0:
                    stale_base = np.asarray(
                        slow.get_parameters()["params"]["w"])
                try:
                    slow.update_parameters(
                        {"params": {"w": lr * (stale_base - target)},
                         "batch_stats": {}})
                except StaleDeltaRejected:
                    rejected += 1
            final = np.asarray(fast.get_parameters()["params"]["w"])
            loss = 0.5 * float(np.sum((final - target) ** 2))
            led = server.ledger.snapshot()["workers"]
            fast.close()
            slow.close()
            return {
                "max_staleness": "inf" if bound is None else bound,
                "soft": soft,
                "final_loss": round(loss, 5),
                "slow_applied": led["slow"]["updates"],
                "slow_damped": led["slow"]["damped"],
                "slow_rejected": led["slow"]["rejected"],
                "client_seen_rejected": rejected,
            }, final
        finally:
            server.stop()

    sweep, h = [], hashlib.sha256()
    for bound in (None, 8, 2):
        arm, final = run_arm(bound)
        sweep.append(arm)
        h.update(np.ascontiguousarray(final).tobytes())

    # Client half of the loop: the AIMD sync-interval ratchet against a
    # max_staleness=2 server. Every wire op is serialized (push then
    # flush), so the interval trajectory is replay-stable.
    from elephas_tpu.engine.async_engine import _CommsPipeline

    server = make_server(
        "socket", {"params": {"w": w0.copy()}, "batch_stats": {}},
        port=0, max_staleness=2,
    )
    server.start()
    try:
        addr = f"127.0.0.1:{server.port}"
        probe = make_client("socket", addr)
        probe.worker_id = "ratchet"
        feeder = make_client("socket", addr)
        feeder.worker_id = "feeder"
        zero = {"params": {"w": np.zeros(dim, np.float32)},
                "batch_stats": {}}
        pipe = _CommsPipeline(probe, 0, 1, sleep=lambda s: None,
                              sync_interval=4.0)
        path = [pipe.sync_interval]
        for i in range(9):
            pipe.pull()
            if i < 3:  # stale window: advance 4 versions behind its back
                for _ in range(4):
                    feeder.get_parameters()
                    feeder.update_parameters(zero)
            pipe.push(zero)
            pipe.flush()
            path.append(round(pipe.sync_interval, 2))
        pipe.close()
        ratchet = {"sync_interval_path": path,
                   "ratchet_rejections": pipe.rejections}
        probe.close()
        feeder.close()
    finally:
        server.stop()

    loss_by = {row["max_staleness"]: row["final_loss"] for row in sweep}
    return {
        "scenario": "staleness",
        "seed": seed,
        "steps": steps,
        "refresh": refresh,
        "staleness_sweep": sweep,
        # Gated bits: the hard bound MUST have refused deltas (the
        # enforcement path ran), bounding staleness must recover
        # convergence lost to unbounded stale applies (absolute floor
        # 0: never worse), and the whole sweep must replay bit-stably.
        "staleness_rejected_nonzero": sweep[-1]["slow_rejected"] > 0,
        "staleness_recovery_gain": round(loss_by["inf"] - loss_by[2], 5),
        "staleness_digest": h.hexdigest()[:16],
        **ratchet,
    }


def scenario_tune(seed: int = 11, trials: int = 9, workers: int = 3):
    """``--tune``: elastic ASHA search under a double chaos arm.

    Three searches over the same seeded trial set:

    - *reference* — undisturbed, in-memory vault. Its winner digest,
      search digest, and epoch accounting are the anchors.
    - *chaos* — checkpoints live on a K=2 socket ``ShardGroup`` through
      a ``GroupVault``, pool worker ``w1`` is killed at its second
      leased rung (``FaultPlan``), and shard 0's primary is crashed
      mid-search (monitor promotes the WAL-streamed spare; the vault's
      client rides the re-resolve path). The gate requires the chaos
      arm to lose ZERO trials and reproduce the reference winner and
      search digests exactly — ASHA's promotion rule is order-invariant
      for the minimum-loss chain, so kills may reorder arrivals but
      never change the winner.
    - *random* — the classic baseline: the same epoch budget the ASHA
      search actually spent, given to full-budget random trials from
      the same sampler stream. ``tune_loss_advantage`` (random best −
      ASHA best) must stay >= 0: halving never does worse than random
      at equal cost, while training a fraction of the epochs.
    """
    from elephas_tpu.parameter.group import ShardGroup
    from elephas_tpu.resilience import FaultInjector, FaultPlan
    from elephas_tpu.tune import GroupVault, hp, sample_trials
    from elephas_tpu.tune.cli import synthetic_trial_fn
    from elephas_tpu.tune.search import run_search

    eta, rungs, r0 = 3, 3, 1
    space = {
        "lr": hp.loguniform(np.log(1e-3), np.log(0.9)),
        "width": hp.choice([32, 64, 128]),
    }

    def slow_trial_fn(config, state, epochs, trial_seed, rung):
        # ~5 ms per epoch: rungs need nonzero wall time so leases
        # spread across the pool and the planned worker kill lands
        # mid-search instead of after one thread drained the queue.
        time.sleep(0.005 * int(epochs))
        return synthetic_trial_fn(config, state, epochs, trial_seed, rung)

    base = run_search(slow_trial_fn, space, num_trials=trials, seed=seed,
                      eta=eta, rungs=rungs, r0=r0, workers=workers)

    # Chaos arm: same seeds, checkpoints on the shard group.
    specs = sample_trials(space, trials, seed)
    template = synthetic_trial_fn(specs[0].config, None, 1,
                                  specs[0].seed, 0)["state"]
    store = GroupVault.build_store([s.trial_id for s in specs], template)
    plan = FaultPlan(seed=seed, kill_worker_at={"w1": 1})
    with tempfile.TemporaryDirectory() as wal_root:
        group = ShardGroup(store, 2, mode="socket", standby=1,
                           wal_root=wal_root, suspect_after=0.3)
        group.start()
        group.start_monitor(interval=0.05)
        ps_killed = threading.Event()

        def kill_shard_later():
            # Mid-search: late enough that checkpoints exist on the
            # shard, early enough that rungs still run after failover.
            time.sleep(0.25)
            group.kill_primary(0)
            ps_killed.set()

        killer = threading.Thread(target=kill_shard_later, daemon=True)
        try:
            vault = GroupVault(group.client())
            killer.start()
            chaos = run_search(slow_trial_fn, space, num_trials=trials,
                               seed=seed, eta=eta, rungs=rungs, r0=r0,
                               workers=workers, vault=vault,
                               injector=FaultInjector(plan))
            killer.join(timeout=10.0)
            # The promoted spare must serve the whole store again.
            final_pull_ok = group.client().get_parameters() is not None
        finally:
            group.stop()

    # Random baseline at the SAME spent budget: every random trial pays
    # the full ladder, so the budget buys only a handful of configs.
    full = eta ** (rungs - 1) * r0
    n_random = max(1, int(base["epochs_spent"]) // full)
    rand_specs = sample_trials(space, n_random, seed)
    random_best = min(
        synthetic_trial_fn(s.config, None, full, s.seed,
                           rungs - 1)["loss"]
        for s in rand_specs)

    return {
        "scenario": "tune",
        "seed": seed,
        "trials": trials,
        "workers": workers,
        "eta": eta,
        "rungs": rungs,
        "tune_epochs_spent": base["epochs_spent"],
        "tune_full_budget_epochs": base["full_budget_epochs"],
        "tune_epochs_saved_frac": round(
            1.0 - base["epochs_spent"] / base["full_budget_epochs"], 4),
        "tune_pruned_frac": round(base["pruned_frac"], 4),
        "tune_best_loss": round(base["best_loss"], 6),
        "random_best_loss": round(random_best, 6),
        "random_epochs_spent": n_random * full,
        "tune_loss_advantage": round(random_best - base["best_loss"], 6),
        "tune_winner_stable": int(
            chaos["winner_digest"] == base["winner_digest"]),
        "tune_search_digest_stable": int(
            chaos["search_digest"] == base["search_digest"]),
        "tune_lost_trials": chaos["lost_trials"],
        "tune_worker_deaths": chaos["pool"]["worker_deaths"],
        "tune_requeued_units": chaos["pool"]["requeued_units"],
        "tune_ps_failovers": len(group.promotions),
        "tune_ps_kill_fired": int(ps_killed.is_set()),
        "tune_final_pull_ok": int(final_pull_ok),
        "winner_digest": base["winner_digest"],
        "search_digest": base["search_digest"],
    }


def export_role_dumps(tracer, outdir, prefix="chaos_trace"):
    """Split the in-process span ring into the per-role dumps a real
    deployment would collect from each process's ``/trace`` route:
    PS-side handle/apply spans (what the server's opsd serves) vs
    everything recorded on the trainer side. Both dumps carry clockSync
    blocks, so the merge exercises the same alignment path as true
    multi-process dumps. Returns ``(worker_path, ps_path)``."""
    from elephas_tpu.obs.trace import export_events

    def is_ps(e):
        return e.name.startswith("ps/handle") or e.name == "ps/apply"

    events = tracer.events()
    worker_path = os.path.join(outdir, f"{prefix}_worker.json")
    ps_path = os.path.join(outdir, f"{prefix}_ps.json")
    export_events([e for e in events if not is_ps(e)], tracer.clock,
                  path=worker_path, process="worker",
                  dropped=tracer.dropped)
    export_events([e for e in events if is_ps(e)], tracer.clock,
                  path=ps_path, process="ps")
    return worker_path, ps_path


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--outage", type=float, default=4.0,
                    help="kill_ps hold-down seconds (keep above the "
                         "~2.8s client retry budget so failures surface)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--health", action="store_true",
                    help="append the training-health row: per-worker "
                         "staleness ledger table, lag histogram + "
                         "percentiles, and the seeded deterministic "
                         "alert-ladder sequence")
    ap.add_argument("--seed", type=int, default=11,
                    help="--health fault-plan + alert-ladder seed (same "
                         "seed → same ordered alert kinds)")
    ap.add_argument("--trace", action="store_true",
                    help="record the run under the obs tracer and emit "
                         "per-role dumps + a merged trace with the "
                         "per-unit critical-path table")
    ap.add_argument("--trace-dir", default=".",
                    help="where --trace writes its three JSON artifacts")
    ap.add_argument("--shards", action="store_true",
                    help="append the shard-kill row: K=2 ShardGroup with "
                         "warm standbys, one primary crashed, measured "
                         "promotion MTTR + zero-acked-loss digest check")
    ap.add_argument("--postmortem", action="store_true",
                    help="append the post-mortem row: shard-kill arc "
                         "with durable telemetry stores, every process "
                         "hard-killed, incident rebuilt from disk alone "
                         "(pinned replay-stable digest + triggering "
                         "event + push-path persistence overhead)")
    ap.add_argument("--staleness", action="store_true",
                    help="append the bounded-staleness row: deterministic "
                         "convergence-vs-max_staleness sweep (∞/8/2) over "
                         "the wire admission path, plus the client "
                         "sync-interval ratchet trajectory")
    ap.add_argument("--fleet", action="store_true",
                    help="append the federation row: kill_ps observed "
                         "through a FleetAggregator polling the PS and "
                         "trainer ops endpoints (stale→dead→alive "
                         "transitions + measured scrape/merge cost)")
    ap.add_argument("--tune", action="store_true",
                    help="append the tuner row: elastic ASHA search with "
                         "a worker killed mid-rung AND a checkpoint-"
                         "shard primary crashed mid-search — winner and "
                         "search digests must match the undisturbed "
                         "reference, zero trials lost, and the spent "
                         "budget must beat same-budget random search")
    args = ap.parse_args(argv)

    tracer = None
    if args.trace:
        from elephas_tpu import obs

        tracer = obs.enable_tracing(capacity=262144, annotate_device=False)

    x, y = make_blobs(args.n)
    rows = [{"scenario": "meta", "epochs": args.epochs, "n": args.n,
             "partitions": 2, "workers": 2, "transport": "socket",
             "expected_units": args.epochs * 2}]
    rows.append(scenario_baseline(x, y, args.epochs))
    rows.append(scenario_kill_ps(x, y, args.epochs, args.outage))
    rows.append(scenario_kill_worker(x, y, args.epochs))
    rows.append(scenario_partition(x, y, args.epochs))
    if args.health:
        rows.append(scenario_health(x, y, args.epochs, seed=args.seed))
    if args.shards:
        rows.append(scenario_shard_kill(seed=args.seed))
    if args.postmortem:
        rows.append(scenario_postmortem(seed=args.seed))
    if args.staleness:
        rows.append(scenario_staleness(seed=args.seed))
    if args.fleet:
        rows.append(scenario_fleet(x, y, args.epochs, args.outage))
    if args.tune:
        rows.append(scenario_tune(seed=args.seed))

    anchor = rows[1]["final_loss"]
    for row in rows[2:]:
        if "final_loss" in row:
            row["loss_vs_baseline"] = round(row["final_loss"] - anchor, 5)

    for row in rows:
        print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")

    if tracer is not None:
        from elephas_tpu import obs

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_report

        worker_path, ps_path = export_role_dumps(tracer, args.trace_dir)
        merged_path = os.path.join(args.trace_dir,
                                   "chaos_trace_merged.json")
        text = trace_report.merge_report([worker_path, ps_path],
                                         out=merged_path)
        print(text, end="")
        obs.disable_tracing()
    return rows


if __name__ == "__main__":
    main()
