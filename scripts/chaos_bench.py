"""Chaos bench: measured recovery behavior of the resilience layer.

Runs a small elastic async fit (socket PS transport, WAL-backed) under
three fault scenarios plus an undisturbed baseline, and emits one JSON
object per scenario so the numbers land as a committed artifact
(``--out BENCH_CHAOS.json``):

- ``{"scenario": "baseline"}`` — undisturbed elastic fit; its
  ``final_loss`` is the tolerance anchor for every chaos arm (same data,
  same seeds, unit-keyed determinism).
- ``{"scenario": "kill_ps"}`` — the parameter server is crashed
  (``SocketServer.kill``: acceptor down, live connections severed, NO
  clean WAL sync) once a few updates are durable, held down for
  ``--outage`` seconds, then warm-restarted on the same port from the
  same WAL dir. Reports worker-observed MTTR samples (outage start →
  first successful reconnect), units re-queued, and the durable version
  the restart resumed from.
- ``{"scenario": "kill_worker"}`` — a ``FaultPlan`` kills one worker
  thread at its second leased unit; the monitor re-queues its pending
  unit to survivors. Reports the re-queue count and the exact
  frequency-unit accounting.
- ``{"scenario": "partition"}`` — a deterministic partition window
  drops every wire frame with ``start <= seq < end``; clients ride
  their retry machinery through it. Reports retry-visible effects and
  the plan's ``trace_digest`` (replays from the same seed match it).

MTTR here is end-to-end as a WORKER experiences it: from the first
failed round trip to the first successful one after recovery — it
includes the bench's own outage hold-down, the client retry backoff,
and reconnect cost, which is the number an operator actually sees.

``--trace`` runs the whole bench under the obs tracer and emits the
distributed-trace artifacts: the in-process ring is split into
per-role dumps (``chaos_trace_worker.json`` — trainer lanes, client
``ps/pull``/``ps/push``, comms queue waits — and ``chaos_trace_ps.json``
— the PS-side ``ps/handle_*``/``ps/apply`` spans, exactly what a remote
PS's ``/trace`` route would have served), then merges them through
``scripts/trace_report.py --merge`` into ``chaos_trace_merged.json``
and prints the per-unit queue/wire/lock/train critical-path table.
Because the wire codec propagates ``(trace_id, span_id)``, the worker
and PS dumps join on trace id exactly as true multi-process dumps do.

Importable without a TPU; tier-1-sized defaults finish in ~1 min on
CPU. Usage:
    python scripts/chaos_bench.py [--epochs 4] [--outage 4.0]
        [--n 256] [--out BENCH_CHAOS.json] [--trace] [--trace-dir D]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def make_blobs(n: int, dim: int = 8, classes: int = 3, seed: int = 3):
    """Gaussian class blobs + one-hot labels (mirrors the test fixture —
    re-implemented here so the bench doesn't import from tests/)."""
    rng = np.random.default_rng(seed)
    centers = rng.standard_normal((classes, dim)) * 3.0
    labels = rng.integers(0, classes, size=n)
    x = centers[labels] + rng.standard_normal((n, dim))
    y = np.eye(classes, dtype=np.float32)[labels]
    return x.astype(np.float32), y


def _build_net():
    from elephas_tpu import compile_model
    from elephas_tpu.models import get_model

    return compile_model(
        get_model("mlp", features=(16,), num_classes=3),
        optimizer={"name": "sgd", "learning_rate": 0.05},
        loss="categorical_crossentropy", metrics=["acc"],
        input_shape=(8,), seed=0,
    )


def _build_trainer(fault_plan=None, wal_dir=None, grace: float = 30.0):
    from elephas_tpu.engine.async_engine import AsyncTrainer
    from elephas_tpu.parallel.mesh import build_mesh

    net = _build_net()
    return AsyncTrainer(
        net, build_mesh(num_data=2), frequency="epoch",
        parameter_server_mode="socket", port=0, elastic=True,
        fault_plan=fault_plan, ps_wal_dir=wal_dir, ps_recovery_grace=grace,
    )


def _run_fit(trainer, x, y, epochs: int, chaos=None):
    """Fit on a worker thread (chaos needs the main thread free to kill
    things); returns (history, stats, wall_seconds, chaos_detail)."""
    from elephas_tpu.data.rdd import ShardedDataset

    result, detail = {}, {}

    def run():
        result["out"] = trainer.fit(ShardedDataset(x, y, 2),
                                    epochs=epochs, batch_size=16)

    t0 = time.perf_counter()
    th = threading.Thread(target=run)
    th.start()
    if chaos is not None:
        detail = chaos(trainer)
    th.join()
    wall = time.perf_counter() - t0
    _, history = result["out"]
    return history, trainer.elastic_stats, wall, detail


def _stats_row(scenario, history, stats, wall, **extra):
    mttr = stats["mttr_samples"]
    return {
        "scenario": scenario,
        "wall_s": round(wall, 2),
        "final_loss": round(float(history["loss"][-1]), 5),
        "completed_units": stats["completed_units"],
        "requeued_units": stats["requeued_units"],
        "worker_deaths": len(stats["worker_deaths"]),
        "ps_outages": len(stats["ps_outages"]),
        "mttr_mean_s": round(sum(mttr) / len(mttr), 3) if mttr else None,
        "mttr_max_s": round(max(mttr), 3) if mttr else None,
        **extra,
    }


def scenario_baseline(x, y, epochs):
    history, stats, wall, _ = _run_fit(_build_trainer(), x, y, epochs)
    return _stats_row("baseline", history, stats, wall)


def scenario_kill_ps(x, y, epochs, outage: float):
    from elephas_tpu.parameter.server import make_server

    def chaos(trainer):
        while trainer._elastic_server is None:
            time.sleep(0.005)
        server = trainer._elastic_server
        port, wal_dir = server.port, trainer.ps_wal_dir
        while server.buffer.version < 3:  # let some updates become durable
            time.sleep(0.005)
        server.kill()
        killed_at = server.buffer.version
        time.sleep(outage)  # outage > client retry budget → real failures
        # Warm restart on the same port: a COLD initial store (as a real
        # supervisor restart would have), immediately superseded by the
        # WAL's newest durable snapshot during construction.
        cold = _build_net()
        fresh = make_server(
            "socket",
            {"params": cold.params, "batch_stats": cold.batch_stats},
            port=port, wal_dir=wal_dir,
        )
        fresh.start()
        trainer._elastic_server = fresh
        return {"durable_version_at_kill": killed_at,
                "resumed_version": fresh.buffer.version,
                "outage_hold_s": outage}

    with tempfile.TemporaryDirectory() as wal_dir:
        trainer = _build_trainer(wal_dir=wal_dir, grace=max(30.0, 4 * outage))
        history, stats, wall, detail = _run_fit(trainer, x, y, epochs,
                                                chaos=chaos)
    return _stats_row("kill_ps", history, stats, wall, **detail)


def scenario_kill_worker(x, y, epochs):
    from elephas_tpu.resilience import FaultPlan

    plan = FaultPlan(seed=11, kill_worker_at={"w1": 1})
    trainer = _build_trainer(fault_plan=plan)
    history, stats, wall, _ = _run_fit(trainer, x, y, epochs)
    return _stats_row("kill_worker", history, stats, wall,
                      trace_digest=hex(plan.trace_digest()))


def scenario_partition(x, y, epochs):
    from elephas_tpu.resilience import FaultPlan

    # Frames 6..14 (per peer, send side) hit the void: mid-fit both
    # workers lose a handful of round trips and retry through them.
    plan = FaultPlan(seed=23, partition={"*": (6, 14)})
    trainer = _build_trainer(fault_plan=plan)
    history, stats, wall, _ = _run_fit(trainer, x, y, epochs)
    return _stats_row("partition", history, stats, wall,
                      trace_digest=hex(plan.trace_digest()))


def export_role_dumps(tracer, outdir, prefix="chaos_trace"):
    """Split the in-process span ring into the per-role dumps a real
    deployment would collect from each process's ``/trace`` route:
    PS-side handle/apply spans (what the server's opsd serves) vs
    everything recorded on the trainer side. Both dumps carry clockSync
    blocks, so the merge exercises the same alignment path as true
    multi-process dumps. Returns ``(worker_path, ps_path)``."""
    from elephas_tpu.obs.trace import export_events

    def is_ps(e):
        return e.name.startswith("ps/handle") or e.name == "ps/apply"

    events = tracer.events()
    worker_path = os.path.join(outdir, f"{prefix}_worker.json")
    ps_path = os.path.join(outdir, f"{prefix}_ps.json")
    export_events([e for e in events if not is_ps(e)], tracer.clock,
                  path=worker_path, process="worker",
                  dropped=tracer.dropped)
    export_events([e for e in events if is_ps(e)], tracer.clock,
                  path=ps_path, process="ps")
    return worker_path, ps_path


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--n", type=int, default=256)
    ap.add_argument("--outage", type=float, default=4.0,
                    help="kill_ps hold-down seconds (keep above the "
                         "~2.8s client retry budget so failures surface)")
    ap.add_argument("--out", default=None)
    ap.add_argument("--trace", action="store_true",
                    help="record the run under the obs tracer and emit "
                         "per-role dumps + a merged trace with the "
                         "per-unit critical-path table")
    ap.add_argument("--trace-dir", default=".",
                    help="where --trace writes its three JSON artifacts")
    args = ap.parse_args(argv)

    tracer = None
    if args.trace:
        from elephas_tpu import obs

        tracer = obs.enable_tracing(capacity=262144, annotate_device=False)

    x, y = make_blobs(args.n)
    rows = [{"scenario": "meta", "epochs": args.epochs, "n": args.n,
             "partitions": 2, "workers": 2, "transport": "socket",
             "expected_units": args.epochs * 2}]
    rows.append(scenario_baseline(x, y, args.epochs))
    rows.append(scenario_kill_ps(x, y, args.epochs, args.outage))
    rows.append(scenario_kill_worker(x, y, args.epochs))
    rows.append(scenario_partition(x, y, args.epochs))

    anchor = rows[1]["final_loss"]
    for row in rows[2:]:
        row["loss_vs_baseline"] = round(row["final_loss"] - anchor, 5)

    for row in rows:
        print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")

    if tracer is not None:
        from elephas_tpu import obs

        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        import trace_report

        worker_path, ps_path = export_role_dumps(tracer, args.trace_dir)
        merged_path = os.path.join(args.trace_dir,
                                   "chaos_trace_merged.json")
        text = trace_report.merge_report([worker_path, ps_path],
                                         out=merged_path)
        print(text, end="")
        obs.disable_tracing()
    return rows


if __name__ == "__main__":
    main()
