"""Parameter-server data-path bench: packed wire codec vs legacy pickle,
version-gated snapshot cache, and pipelined vs serial worker comms.

Emits one JSON object per measurement so the numbers land as a committed
artifact (``--out BENCH_PS.json``):

- ``{"mode": "codec", "codec": "packed" | "pickle", "op": ...}`` —
  serialize/deserialize throughput (MB/s) of a ResNet-18-sized float32
  tree (~11.7M params / ~46.8 MB). ``op`` is ``encode`` (server-side
  pull serialize / client-side push serialize; packed counts its
  scatter-gather chunk assembly, the form the socket layer actually
  sends) or ``decode`` (packed returns ``np.frombuffer`` views — the
  zero-copy claim is THIS row). ``quantize`` rows show the bf16/f16
  push-bytes halving.
- ``{"mode": "cache"}`` — wire bytes of a cache MISS (full packed
  frame, O(model)) vs a cache HIT (12-byte not-modified frame,
  O(header)), plus the measured hit/miss reply latency against a live
  ``HttpServer``.
- ``{"mode": "transport", ...}`` — live end-to-end pull+push round
  trips/sec over HTTP loopback, packed vs pickle arm.
- ``{"mode": "pipeline", "pipelined": bool}`` — per-unit wall time of a
  simulated worker loop (pull → train → push, train simulated as a
  fixed sleep) against a live server: the serial arm pays
  train+wire per unit, the pipelined arm overlaps them via
  ``_CommsPipeline`` prefetch + fire-and-forget push.
- ``{"mode": "shards", "op": "pull_k<K>" | "push_k<K>" |
  "refresh_k<K>"}`` — the ShardGroup data path at K=1/2/4 socket
  shards (K is baked into ``op`` so the gate's identity key separates
  the arms). ``pull``/``push`` are dense full-tree scatter/gather;
  ``refresh`` is the single-shard-dirty cycle: one shard's version
  advances and a worker re-pulls its full consistent view. Dense arms
  track fan-out overhead (on a loopback single process they cannot
  show parallel speedup — every shard shares the host's cores); the
  refresh arm carries the scaling claim that IS core-independent:
  per-shard version gating means the K-1 clean shards answer with
  12-byte not-modified frames, so the effective full-view refresh
  bandwidth grows ~K×. The K=4 refresh row's ``ps_shard_bw_ratio``
  (vs the K=1 refresh arm) is held above an absolute floor by
  ``bench_gate.py``.

Importable (and runnable with tiny defaults) without a TPU — wire+codec
paths are pure numpy/sockets; real numbers come from the dev host.

Usage: python scripts/ps_bench.py [--reps 5] [--units 30]
       [--train-ms 25] [--small] [--shards] [--out BENCH_PS.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def resnet18_tree(small: bool = False) -> dict:
    """A ResNet-18-shaped float32 parameter tree (~11.7M params).

    Shapes follow the torchvision layout (conv1 7x7x3x64, four stages of
    two basic blocks, fc 512x1000); exact micro-architecture doesn't
    matter — the bench needs the leaf-count/size DISTRIBUTION (many
    medium conv kernels + one big fc) more than the wiring.
    """
    if small:  # tier-1 smoke: same structure, 1/8 channel widths
        widths, fc_in = [8, 16, 32, 64], 64
    else:
        widths, fc_in = [64, 128, 256, 512], 512
    rng = np.random.default_rng(0)

    def conv(cin, cout, k=3):
        return rng.standard_normal((k, k, cin, cout)).astype(np.float32)

    tree = {"conv1": {"kernel": conv(3, widths[0], 7)},
            "bn1": {"scale": np.ones(widths[0], np.float32),
                    "bias": np.zeros(widths[0], np.float32)}}
    cin = widths[0]
    for stage, cout in enumerate(widths):
        for block in range(2):
            name = f"layer{stage + 1}_block{block}"
            tree[name] = {
                "conv1": {"kernel": conv(cin, cout)},
                "bn1": {"scale": np.ones(cout, np.float32),
                        "bias": np.zeros(cout, np.float32)},
                "conv2": {"kernel": conv(cout, cout)},
                "bn2": {"scale": np.ones(cout, np.float32),
                        "bias": np.zeros(cout, np.float32)},
            }
            if block == 0 and cin != cout:
                tree[name]["downsample"] = {"kernel": conv(cin, cout, 1)}
            cin = cout
    tree["fc"] = {"kernel": rng.standard_normal((fc_in, 1000)).astype(np.float32),
                  "bias": np.zeros(1000, np.float32)}
    return tree


def tree_nbytes(tree) -> int:
    import jax

    return sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(tree))


def _time(fn, reps: int) -> float:
    """Best-of-reps seconds (min filters scheduler noise on loopback)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fn()
        best = min(best, time.perf_counter() - t0)
    return best


def bench_codec(tree, reps: int):
    from elephas_tpu.parameter import wire

    nbytes = tree_nbytes(tree)
    mb = nbytes / 1e6
    rows = []

    packed_buf = wire.encode_tree(tree).tobytes()
    pickle_buf = wire.encode_pickle(tree)

    arms = [
        ("packed", "encode", None, lambda: wire.encode_tree(tree)),
        ("pickle", "encode", None, lambda: wire.encode_pickle(tree)),
        ("packed", "decode", None, lambda: wire.decode(packed_buf)),
        ("pickle", "decode", None, lambda: wire.decode_pickle(pickle_buf)),
        ("packed", "encode", "bf16",
         lambda: wire.encode_tree(tree, quantize="bf16")),
        ("packed", "encode", "f16",
         lambda: wire.encode_tree(tree, quantize="f16")),
    ]
    for codec, op, quantize, fn in arms:
        secs = _time(fn, reps)
        wire_bytes = nbytes
        if quantize:
            wire_bytes = wire.encode_tree(tree, quantize=quantize).nbytes
        rows.append({
            "mode": "codec", "codec": codec, "op": op, "quantize": quantize,
            "tree_mb": round(mb, 2), "wire_mb": round(wire_bytes / 1e6, 2),
            "secs": secs, "mb_per_s": round(mb / secs, 1),
        })
    return rows


def bench_cache(tree, reps: int):
    from elephas_tpu.parameter import wire
    from elephas_tpu.parameter.server import HttpServer

    full = wire.encode_tree(tree, version=0).nbytes
    notmod = wire.encode_not_modified(0).nbytes
    rows = [{
        "mode": "cache", "miss_bytes": full, "hit_bytes": notmod,
        "ratio": round(full / notmod, 1),
    }]

    server = HttpServer(tree, lock=True, port=0)
    server.start()
    try:
        client = server.client()
        client.get_parameters()  # prime: snapshot cache + client version
        hit = _time(client.get_parameters, reps)  # unchanged → not-modified

        def miss():
            server.buffer._version += 1  # invalidate without re-training
            client.get_parameters()

        miss_secs = _time(miss, reps)
        rows.append({
            "mode": "cache", "op": "pull_latency",
            "hit_secs": hit, "miss_secs": miss_secs,
            "speedup": round(miss_secs / hit, 1),
        })
    finally:
        server.stop()
    return rows


def bench_transport(tree, reps: int):
    from elephas_tpu.parameter.client import HttpClient
    from elephas_tpu.parameter.server import HttpServer

    mb = tree_nbytes(tree) / 1e6
    rows = []
    for codec in ("packed", "pickle"):
        server = HttpServer(tree, lock=True, port=0)
        server.start()
        try:
            client = HttpClient(f"127.0.0.1:{server.port}", codec=codec)

            def unit():
                # version bump forces a full-body pull (no cache hit):
                # this arm measures codec throughput, not the cache.
                server.buffer._version += 1
                pulled = client.get_parameters()
                client.update_parameters(pulled)

            secs = _time(unit, reps)
            rows.append({
                "mode": "transport", "codec": codec, "tree_mb": round(mb, 2),
                "secs_per_roundtrip": secs,
                "mb_per_s": round(2 * mb / secs, 1),  # pull + push
            })
        finally:
            server.stop()
    return rows


def bench_pipeline(tree, units: int, train_ms: float):
    """Per-unit wall time: serial pull→train→push vs pipelined comms."""
    from elephas_tpu.engine.async_engine import _CommsPipeline
    from elephas_tpu.parameter.server import HttpServer

    rows = []
    for pipelined in (False, True):
        server = HttpServer(tree, lock=True, port=0)
        server.start()
        try:
            client = server.client()
            comms = _CommsPipeline(client, 0, max_push_attempts=3) \
                if pipelined else None
            t0 = time.perf_counter()
            for _ in range(units):
                server.buffer._version += 1  # force full-body pulls
                if comms is not None:
                    pulled = comms.pull()
                    comms.prefetch()
                else:
                    pulled = client.get_parameters()
                time.sleep(train_ms / 1e3)  # stand-in for the train step
                if comms is not None:
                    comms.push(pulled)
                else:
                    client.update_parameters(pulled)
            if comms is not None:
                comms.flush()
                comms.close()
            total = time.perf_counter() - t0
            rows.append({
                "mode": "pipeline", "pipelined": pipelined, "units": units,
                "train_ms": train_ms,
                "secs_per_unit": total / units,
                "wire_overhead_ms": round(
                    (total / units - train_ms / 1e3) * 1e3, 2),
            })
        finally:
            server.stop()
    return rows


def bench_shards(tree, reps: int, shard_counts=(1, 2, 4)):
    """ShardGroup data path: dense scatter/gather + sparse refresh.

    Per K: one live socket group, one sharded client. ``pull``/``push``
    bump every shard first (no arm hides behind the not-modified cache)
    and move the whole tree — the fan-out overhead rows. ``refresh``
    advances ONE shard's version and re-pulls the full consistent view:
    the K-1 clean shards answer 12-byte not-modified frames, so the
    bytes on the wire shrink ~K× and the effective view-refresh
    bandwidth (full tree MB per refresh second) grows with K on any
    host — byte economy, not parallelism, which is why THIS row carries
    the gated ``ps_shard_bw_ratio``.
    """
    from elephas_tpu.parameter.group import ShardGroup

    mb = tree_nbytes(tree) / 1e6
    rows = []
    bw = {}
    for k in shard_counts:
        group = ShardGroup(tree, k, mode="socket")
        group.start()
        try:
            client = group.client()
            client.get_parameters()  # prime dials + snapshot caches

            def pull():
                for i in range(k):
                    group.primary(i).buffer._version += 1
                client.get_parameters()

            def push():
                client.update_parameters(tree)

            def refresh():
                group.primary(0).buffer._version += 1
                client.get_parameters()

            for op, fn in (("pull", pull), ("push", push),
                           ("refresh", refresh)):
                secs = _time(fn, reps)
                bw[(op, k)] = mb / secs
                row = {
                    "mode": "shards", "codec": "packed", "op": f"{op}_k{k}",
                    "quantize": None, "pipelined": None, "shards": k,
                    "tree_mb": round(mb, 2), "secs": secs,
                    "mb_per_s": round(mb / secs, 1),
                }
                if k != 1 and (op, 1) in bw:
                    row["shard_bw_ratio"] = round(bw[(op, k)] / bw[(op, 1)],
                                                  2)
                if op == "refresh" and k == max(shard_counts) \
                        and ("refresh", 1) in bw:
                    row["ps_shard_bw_ratio"] = row["shard_bw_ratio"]
                rows.append(row)
            client.close()
        finally:
            group.stop()
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--units", type=int, default=30)
    ap.add_argument("--train-ms", type=float, default=25.0)
    ap.add_argument("--small", action="store_true",
                    help="1/8-width tree (tier-1 smoke)")
    ap.add_argument("--shards", action="store_true",
                    help="append the ShardGroup aggregate-bandwidth arm "
                         "(K=1/2/4 socket shards)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args(argv)

    tree = resnet18_tree(small=args.small)
    n_params = tree_nbytes(tree) // 4
    rows = [{"mode": "meta", "params": n_params,
             "tree_mb": round(tree_nbytes(tree) / 1e6, 2),
             "small": args.small}]
    rows += bench_codec(tree, args.reps)
    rows += bench_cache(tree, args.reps)
    rows += bench_transport(tree, args.reps)
    rows += bench_pipeline(tree, args.units, args.train_ms)
    if args.shards:
        rows += bench_shards(tree, args.reps)

    for row in rows:
        print(json.dumps(row))
    if args.out:
        with open(args.out, "w") as f:
            for row in rows:
                f.write(json.dumps(row) + "\n")
    return rows


if __name__ == "__main__":
    main()
