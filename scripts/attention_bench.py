"""Long-context attention bench: Pallas flash kernels vs the XLA
blockwise fallback, fwd+bwd, on the real chip (SURVEY.md §5.7 upgrade).

Emits one JSON line per (seq_len, impl) with ms/step and achieved
throughput so the speedup is a committed artifact rather than something
each reviewer re-measures (r2 VERDICT verified 2.05x at seq 8192 by
hand — this script reproduces that table).

Usage: python scripts/attention_bench.py [--seqs 2048 4096 8192] [--steps 20]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def build(seq: int, impl: str, heads: int = 8, dim: int = 64, batch: int = 1):
    from elephas_tpu.ops import attention as attn

    def loss_fn(q, k, v):
        # 'pallas'/'xla_custom_vjp' force their kernel through the SHIPPED
        # custom-VJP path regardless of the public API's pallas_min_seq
        # dispatch (this script MEASURES the crossover that dispatch
        # encodes, so both arms must be what production actually runs);
        # 'xla_autodiff' is the plain-autodiff lower bound for context.
        import unittest.mock as mock

        from elephas_tpu.ops.attention_pallas import default_blocks

        bq, bk = default_blocks(q.shape[2])  # the SHIPPED per-length tiling
        if impl == "pallas":
            with mock.patch.object(attn, "_use_pallas", lambda q_: True):
                out = attn._flash(q, k, v, True, bq, bk)
        elif impl == "xla_custom_vjp":
            with mock.patch.object(attn, "_use_pallas", lambda q_: False):
                out = attn._flash(q, k, v, True, bq, bk)
        else:
            out = attn._blockwise_reference(q, k, v, True, bq, bk)
        return jnp.sum(out.astype(jnp.float32) ** 2)

    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
    rng = np.random.default_rng(0)
    shape = (batch, heads, seq, dim)
    q, k, v = (
        jax.device_put(rng.normal(size=shape).astype(np.float32).astype(jnp.bfloat16))
        for _ in range(3)
    )
    return grad_fn, (q, k, v)


def build_ring(tokens_per_shard: int, impl: str, heads: int = 8, dim: int = 64,
               batch: int = 1):
    """Ring arm (VERDICT r3 #4): dense-hop vs flash-hop ring attention at
    a given tokens/shard, fwd+bwd through the shipped custom-VJP path.
    On this 1-chip env the seq axis is size 1 — the ring degenerates to
    its per-hop kernel, which is exactly what the dense-vs-flash hop
    comparison measures (rotation is ICI traffic either way)."""
    from jax.sharding import PartitionSpec as P

    from elephas_tpu.parallel.mesh import SEQ_AXIS, build_mesh
    from elephas_tpu.parallel.ring_attention import ring_attention

    n_seq = 1  # all local devices on the seq axis would also work; bench 1
    mesh = build_mesh(num_data=1, num_seq=n_seq)
    spec = P(None, None, SEQ_AXIS, None)

    def body(q_, k_, v_):
        out = ring_attention(q_, k_, v_, axis_name=SEQ_AXIS, causal=True,
                             impl=impl)
        return jax.lax.psum(jnp.sum(out.astype(jnp.float32) ** 2), SEQ_AXIS)

    loss_fn = jax.shard_map(
        body, mesh=mesh, in_specs=(spec, spec, spec), out_specs=P(),
        check_vma=False,
    )
    grad_fn = jax.jit(jax.value_and_grad(loss_fn, argnums=(0, 1, 2)))
    rng = np.random.default_rng(0)
    shape = (batch, heads, tokens_per_shard * n_seq, dim)
    q, k, v = (
        jax.device_put(rng.normal(size=shape).astype(np.float32).astype(jnp.bfloat16))
        for _ in range(3)
    )
    return grad_fn, (q, k, v)


def measure(fn, args, steps: int, warmup: int = 3) -> float:
    for _ in range(warmup):
        loss, grads = fn(*args)
    float(loss)  # force the chain (axon: block_until_ready lies)
    t0 = time.perf_counter()
    for _ in range(steps):
        loss, grads = fn(*args)
    float(loss)
    return (time.perf_counter() - t0) / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--seqs", type=int, nargs="*", default=[2048, 4096, 8192])
    ap.add_argument("--dims", type=int, nargs="*", default=[64],
                    help="head_dims to sweep (the crossover is "
                         "shape-dependent — ops.attention.pallas_min_seq)")
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--impls", nargs="*",
                    default=["xla_autodiff", "xla_custom_vjp", "pallas"])
    ap.add_argument("--ring", action="store_true",
                    help="bench the ring arms (dense-hop vs flash-hop) "
                         "at --seqs tokens/shard instead of the "
                         "single-device kernels")
    args = ap.parse_args()

    print(f"devices={jax.devices()}", file=sys.stderr)
    if args.ring:
        by_seq = {}
        for seq in args.seqs:
            for impl in ("dense", "flash"):
                fn, data = build_ring(seq, impl)
                sec = measure(fn, data, args.steps)
                by_seq.setdefault(seq, {})[impl] = sec
                print(json.dumps({
                    "tokens_per_shard": seq, "ring_impl": impl,
                    "fwd_bwd_ms": round(sec * 1e3, 2),
                }), flush=True)
                del fn, data
        for seq, r in by_seq.items():
            print(json.dumps({
                "tokens_per_shard": seq,
                "speedup_flash_ring_vs_dense_ring": round(
                    r["dense"] / r["flash"], 2
                ),
            }), flush=True)
        return
    for dim in args.dims:
        by_seq = {}
        for seq in args.seqs:
            for impl in args.impls:
                fn, data = build(seq, impl, dim=dim)
                sec = measure(fn, data, args.steps)
                by_seq.setdefault(seq, {})[impl] = sec
                print(json.dumps({
                    "seq": seq, "head_dim": dim, "impl": impl,
                    "fwd_bwd_ms": round(sec * 1e3, 2),
                }), flush=True)
                del fn, data
        for seq, r in by_seq.items():
            # The threshold decision compares the two SHIPPED paths.
            if "xla_custom_vjp" in r and "pallas" in r:
                print(json.dumps({
                    "seq": seq, "head_dim": dim,
                    "speedup_pallas_vs_xla_custom_vjp": round(
                        r["xla_custom_vjp"] / r["pallas"], 2
                    ),
                }), flush=True)


if __name__ == "__main__":
    main()
