"""Perf sweep for the flagship CIFAR-10 ResNet-18 train step (VERDICT r2 #1).

Measures samples/sec for a grid of {batch size × norm dtype × input dtype}
variants of the exact step bench.py times, plus XLA's own FLOP estimate so
MFU can be stated honestly. Optionally captures a jax.profiler trace of
the best variant (--trace DIR).

Usage:  python scripts/perf_sweep.py [--trace /tmp/trace] [--steps 30]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import numpy as np


def build_step(norm_dtype: str, batch: int, input_dtype: str):
    from elephas_tpu.api.compile import CompiledModel
    from elephas_tpu.engine.step import init_train_state, make_train_step
    from elephas_tpu.models import get_model

    module = get_model(
        "resnet18", num_classes=10, width=64, dtype="bfloat16", norm_dtype=norm_dtype
    )
    compiled = CompiledModel(
        module,
        optimizer={"name": "momentum", "learning_rate": 0.1},
        loss="categorical_crossentropy",
        metrics=["acc"],
        input_shape=(32, 32, 3),
    )
    rng = np.random.default_rng(0)
    x = rng.normal(size=(batch, 32, 32, 3)).astype(input_dtype)
    y = np.eye(10, dtype=np.float32)[rng.integers(0, 10, size=batch)]
    device = jax.devices()[0]
    x, y = jax.device_put(x, device), jax.device_put(y, device)
    from elephas_tpu.utils.compiler import tpu_compiler_options

    # Same compile options as bench.py/the shipped trainers — the sweep
    # must measure the program production actually runs.
    step = jax.jit(
        make_train_step(compiled), donate_argnums=(0,),
        compiler_options=tpu_compiler_options(),
    )
    state = jax.device_put(init_train_state(compiled), device)
    return step, state, x, y


def measure(step, state, x, y, steps: int, warmup: int = 5):
    for _ in range(warmup):
        state, metrics = step(state, x, y)
    float(metrics["loss"])  # force the chain (axon: block_until_ready lies)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, x, y)
    float(metrics["loss"])
    dt = time.perf_counter() - t0
    return dt / steps, state


def flops_estimate(step, state, x, y) -> float:
    try:
        cost = step.lower(state, x, y).compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost.get("flops", 0.0))
    except Exception as exc:  # cost analysis is best-effort
        print(f"  (cost_analysis unavailable: {exc})", file=sys.stderr)
        return 0.0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--trace", type=str, default=None,
                    help="capture a profiler trace of the best variant here")
    ap.add_argument("--batches", type=int, nargs="*", default=[512, 1024, 2048])
    args = ap.parse_args()

    print(f"devices={jax.devices()}", file=sys.stderr)
    results = []
    for norm_dtype in ("float32", "bfloat16"):
        for input_dtype in ("float32", "bfloat16"):
            for batch in args.batches:
                step, state, x, y = build_step(norm_dtype, batch, input_dtype)
                fl = flops_estimate(step, state, x, y)
                sec, state = measure(step, state, x, y, args.steps)
                rate = batch / sec
                tflops = fl / sec / 1e12 if fl else 0.0
                row = {
                    "batch": batch,
                    "norm_dtype": norm_dtype,
                    "input_dtype": input_dtype,
                    "step_ms": round(sec * 1e3, 3),
                    "samples_per_sec": round(rate, 1),
                    "xla_flops_per_step": fl,
                    "achieved_tflops": round(tflops, 1),
                }
                results.append(row)
                print(json.dumps(row), flush=True)
                del step, state, x, y

    best = max(results, key=lambda r: r["samples_per_sec"])
    print("# best:", json.dumps(best))

    if args.trace:
        step, state, x, y = build_step(best["norm_dtype"], best["batch"],
                                       best["input_dtype"])
        sec, state = measure(step, state, x, y, 5)  # warm/compiled
        with jax.profiler.trace(args.trace):
            for _ in range(10):
                state, metrics = step(state, x, y)
            float(metrics["loss"])
        print(f"# trace written to {args.trace}", file=sys.stderr)


if __name__ == "__main__":
    main()
