#!/usr/bin/env python
"""fleet_top: one-shot (or interval) text view over N ops endpoints.

The terminal counterpart of the ``/fleet`` route: point it at every
process's opsd URL and get the merged picture — who is alive/stale/dead
(with boot ids, so a warm restart is visible as the same slot coming
back different), per-process LOAD (EWMA saturation score from ``/load``)
and GOODPUT (worst-objective SLO attainment from ``/slo``; both render
``-`` for stale/dead procs), SPEC (speculative-decode accept rate and
realized tokens/step from the ``/load`` signals; ``-`` for engines not
speculating), DISK (durable telemetry journal bytes from
the federated ``obs_store_bytes`` gauge + seconds since the last
persisted record via ``/incidents``; ``-`` when stale/dead or no store
is mounted), the fleet-summed counters, pooled histogram
percentiles, cluster worker ledger, and active alerts. A process whose
``/replicas`` roster is non-empty (a fleet router) also gets a replica
board: per-replica lifecycle STATE, serving TIER (prefill/decode/mono;
``-`` for pre-disagg routers), boot, LOAD, affinity hit-rate,
in-flight count, and worst burn — all ``-`` when the router itself went
stale/dead, and the signal columns ``-`` for dead replicas. A router
running disaggregated tiers (non-empty ``/tiers``) also gets a TIERS
board: per-tier replica counts, KV-handoff count/failures/latency
percentiles, tier imbalance, and the QoS policy card (per-tenant
bucket fill, priority class, fair-share vtime, throttle and
preemption counts). A process
whose ``/tenants`` cost ledger is non-empty also gets a TENANTS board:
per-tenant requests, prefill/decode tokens, KV block-seconds, spec
accept rate, goodput and burn — untagged traffic renders as tenant
``default`` (never dropped), stale/dead procs as ``-`` throughout.

Usage:
    python scripts/fleet_top.py http://127.0.0.1:8801 http://127.0.0.1:8802
    python scripts/fleet_top.py --interval 2 ps=http://127.0.0.1:8801 \
        w0=http://127.0.0.1:8802
    python scripts/fleet_top.py --json http://127.0.0.1:8801

Endpoints may be bare URLs (auto-named ``proc0``, ``proc1``, …) or
``name=url`` pairs. ``--interval`` repolls forever (Ctrl-C to stop);
``--json`` dumps the raw merged snapshot instead of the table. The
aggregator never drops an unreachable process — it goes stale, then
dead after ``--dead-after`` seconds, and stays on the board.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from elephas_tpu.obs.fleet import FleetAggregator  # noqa: E402


def _load_cell(snap: dict, name: str, status: str) -> str:
    """LOAD column: the EWMA saturation score from the proc's /load
    snapshot. A stale/dead process renders '-' — a router dispatching
    on a score that stopped updating is worse than knowing nothing."""
    if status != "alive":
        return "-"
    doc = (snap.get("load") or {}).get(name) or {}
    score = doc.get("score")
    return f"{score:.2f}" if score is not None else "-"


def _kv_cell(snap: dict, name: str, status: str) -> str:
    """KV column: block-granular cache pressure from the proc's /load
    signals — free/total KV blocks plus the prefix-cache hit rate in
    parentheses when the engine has one (paged pools only; contiguous
    pools and non-serving procs render '-')."""
    if status != "alive":
        return "-"
    doc = (snap.get("load") or {}).get(name) or {}
    sig = doc.get("signals") or {}
    total = sig.get("kv_blocks_total")
    if not total:
        return "-"
    cell = f"{sig.get('kv_blocks_free', '?')}/{total}"
    rate = sig.get("prefix_hit_rate")
    if rate is not None:
        cell += f"({100.0 * rate:.0f}%)"
    return cell


def _spec_cell(snap: dict, name: str, status: str) -> str:
    """SPEC column: speculative-decode health from the proc's /load
    signals — draft accept rate with realized tokens/step in
    parentheses. '-' for stale/dead procs and for engines not running
    speculative decode (the signals are absent by construction, same
    contract as the KV column for contiguous pools)."""
    if status != "alive":
        return "-"
    doc = (snap.get("load") or {}).get(name) or {}
    sig = doc.get("signals") or {}
    rate = sig.get("spec_accept_rate")
    if rate is None:
        return "-"
    cell = f"{100.0 * rate:.0f}%"
    tps = sig.get("spec_tokens_per_step")
    if tps is not None:
        cell += f"({tps:.1f})"
    return cell


def _goodput_cell(snap: dict, name: str, status: str) -> str:
    """GOODPUT column: the proc's worst-objective goodput ratio from
    its /slo snapshot, as a percentage; '-' when stale/dead or before
    any finished traffic."""
    if status != "alive":
        return "-"
    doc = (snap.get("slo") or {}).get(name) or {}
    ratio = doc.get("goodput_ratio")
    return f"{100.0 * ratio:.1f}%" if ratio is not None else "-"


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "K", "M", "G"):
        if n < 1024 or unit == "G":
            return f"{n:.0f}{unit}" if unit == "B" else f"{n:.1f}{unit}"
        n /= 1024.0
    return f"{n:.1f}G"


def _disk_cell(snap: dict, name: str, status: str) -> str:
    """DISK column: the proc's durable telemetry footprint — journal
    bytes from the federated ``obs_store_bytes`` gauge plus seconds
    since it last persisted a record (from ``/incidents`` meta). A
    stale/dead process renders '-': its last-known byte count says
    nothing about whether the journal is still being written, and the
    post-mortem CLI reads the store from disk anyway."""
    if status != "alive":
        return "-"
    gauges = (snap.get("metrics") or {}).get("gauges") or {}
    total = sum(v for k, v in gauges.items()
                if k.startswith("obs_store_bytes{")
                and f'proc="{name}"' in k)
    meta = ((snap.get("incidents") or {}).get(name) or {}).get("meta") or {}
    if not total and not meta:
        return "-"
    cell = _fmt_bytes(total)
    age = meta.get("last_record_age_s")
    if age is not None:
        cell += f"/{age:.0f}s"
    return cell


def _sync_cell(row: dict) -> str:
    """SYNC column of the cluster worker ledger: the worker's
    self-reported adaptive units-per-push interval, with its rejected
    delta count in parentheses when the admission policy has refused
    any. Unstamped legacy workers (no ``sync_interval`` in their row)
    render '-' — they predate the ratchet wire stamp."""
    interval = row.get("sync_interval")
    cell = f"{interval:.2f}" if interval is not None else "-"
    rejected = row.get("rejected")
    if rejected:
        cell += f"(rej={rejected})"
    return cell


def _replica_cells(rid: str, card: dict, proc_status: str) -> str:
    """One row of the replica board. Every signal column renders '-'
    when the router process itself is stale/dead (its roster stopped
    updating) and for dead replicas (their signals are None by
    construction — a dead engine has no load score)."""
    alive = proc_status == "alive"

    def num(v):
        return f"{v:.2f}" if alive and v is not None else "-"

    aff = card.get("affinity") or {}
    hits = aff.get("hits", 0)
    misses = aff.get("misses", 0)
    total = hits + misses
    rate = f"{100.0 * hits / total:.0f}%" if alive and total else "-"
    state = str(card.get("state", "?")) if alive else "-"
    # Pre-disagg routers don't stamp a tier — render '-' rather than
    # guessing mono; the column must tell old from new honestly.
    tier = str(card.get("tier") or "-") if alive else "-"
    boot = str(card.get("boot", "-")) if alive else "-"
    inflt = str(card.get("in_flight", "-")) if alive else "-"
    # Served weight version (rollout plane). '-' for stale/dead procs
    # and dead replicas (no engine → no version); a '*' suffix marks
    # the rollout canary mid-bake.
    ver = card.get("model_version")
    version = str(ver) if alive and ver is not None else "-"
    if alive and card.get("rollout_canary"):
        version += "*"
    return (f"{rid:<9} {state:<9} {tier:<8} {boot:>4} "
            f"{num(card.get('load_score')):>6} {rate:>8} {inflt:>6} "
            f"{num(card.get('burn_worst')):>6} {version:>8}")


def render(snap: dict) -> str:
    """The merged fleet snapshot as a fixed-width text board."""
    lines: List[str] = []
    counts = snap["status_counts"]
    summary = "  ".join(f"{k}={v}" for k, v in sorted(counts.items()))
    lines.append(f"fleet: {len(snap['processes'])} processes  {summary}"
                 f"  polls={snap['polls']}")
    lines.append("")
    # ROLE is 12 wide: shard-group members report differentiated roles
    # ("ps/shard0", "ps/standby"), not just the flat "ps"/"worker".
    lines.append(f"{'NAME':<10} {'ROLE':<12} {'STATUS':<7} {'BOOT':<14} "
                 f"{'WORKER':<8} {'LAST OK':>8} {'LOAD':>5} {'GOODPUT':>8} "
                 f"{'KV':>13} {'SPEC':>10} {'DISK':>11}  URL")
    for name, p in sorted(snap["processes"].items()):
        meta = p.get("meta") or {}
        ago = p.get("last_ok_s_ago")
        lines.append(
            f"{name:<10} {str(meta.get('role', '?')):<12} "
            f"{p['status']:<7} {str(meta.get('boot', ''))[:14]:<14} "
            f"{str(meta.get('worker_id') or '-'):<8} "
            f"{('%.1fs' % ago) if ago is not None else '-':>8} "
            f"{_load_cell(snap, name, p['status']):>5} "
            f"{_goodput_cell(snap, name, p['status']):>8} "
            f"{_kv_cell(snap, name, p['status']):>13} "
            f"{_spec_cell(snap, name, p['status']):>10} "
            f"{_disk_cell(snap, name, p['status']):>11}  {p['url']}"
        )
    metrics = snap["metrics"]
    if metrics["counters"]:
        lines.append("")
        lines.append("counters (fleet sum):")
        for key, v in sorted(metrics["counters"].items()):
            lines.append(f"  {key:<56} {v:g}")
    if metrics["histograms"]:
        lines.append("")
        lines.append(f"{'histogram (pooled)':<44} {'count':>8} "
                     f"{'p50':>10} {'p95':>10} {'p99':>10}")
        for key, h in sorted(metrics["histograms"].items()):
            def fmt(x):
                return f"{x:.4g}" if x is not None else "-"
            lines.append(f"  {key:<42} {h['count']:>8} "
                         f"{fmt(h['p50']):>10} {fmt(h['p95']):>10} "
                         f"{fmt(h['p99']):>10}")
    for proc, doc in sorted((snap.get("replicas") or {}).items()):
        proc_status = (snap["processes"].get(proc) or {}).get("status", "?")
        router = doc.get("router") or {}

        def rstat(key):
            v = router.get(key)
            return v if proc_status == "alive" and v is not None else "-"

        lines.append("")
        lines.append(f"replicas via {proc}: requests={rstat('requests')} "
                     f"requeues={rstat('requeues')} "
                     f"sessions={rstat('sessions')}")
        lines.append(f"  {'REPLICA':<9} {'STATE':<9} {'TIER':<8} {'BOOT':>4} "
                     f"{'LOAD':>6} {'AFF HIT':>8} {'INFLT':>6} {'BURN':>6} "
                     f"{'VERSION':>8}")
        for rid, card in sorted((doc.get("replicas") or {}).items()):
            lines.append("  " + _replica_cells(rid, card, proc_status))
    for proc, doc in sorted((snap.get("tiers") or {}).items()):
        # Disaggregated-serving board (/tiers): handoff health plus the
        # QoS policy card — per-tenant bucket fill, priority class,
        # fair-share vtime, throttle/preemption counts. Stale/dead
        # routers render '-' everywhere, same contract as every board.
        proc_status = (snap["processes"].get(proc) or {}).get("status", "?")
        alive = proc_status == "alive"
        hand = doc.get("handoffs") or {}

        def hstat(key, fmt="{}"):
            v = hand.get(key)
            return fmt.format(v) if alive and v is not None else "-"

        lines.append("")
        lines.append(
            f"tiers via {proc}: "
            + "  ".join(
                f"{t}={len((c or {}).get('replicas') or [])}"
                for t, c in sorted((doc.get("tiers") or {}).items()))
            + f"  handoffs={hstat('count')} fails={hstat('fails')} "
            f"p50={hstat('p50_ms', '{:.1f}ms')} "
            f"p99={hstat('p99_ms', '{:.1f}ms')}"
            + (f"  imbalance={doc.get('imbalance'):.2f}"
               if alive and doc.get("imbalance") is not None else ""))
        qos = doc.get("qos") or {}
        if qos.get("tenants"):
            lines.append(f"  {'TENANT':<12} {'PRIO':>4} {'WEIGHT':>6} "
                         f"{'BUCKET':>7} {'VTIME':>9} {'ADMIT':>6} "
                         f"{'THROT':>6} {'PREEMPT':>7}")
            for tenant, row in sorted(qos["tenants"].items()):
                def qcell(key, fmt="{}"):
                    v = row.get(key)
                    return fmt.format(v) if alive and v is not None else "-"

                fill = row.get("bucket_fill")
                lines.append(
                    f"  {tenant:<12} {qcell('priority'):>4} "
                    f"{qcell('weight', '{:.1f}'):>6} "
                    f"{(f'{100.0 * fill:.0f}%' if alive and fill is not None else '-'):>7} "
                    f"{qcell('vtime', '{:.1f}'):>9} {qcell('admitted'):>6} "
                    f"{qcell('throttled'):>6} {qcell('preempted'):>7}")
    for proc, doc in sorted((snap.get("rollout") or {}).items()):
        # Live-model-delivery board (/rollout): the canary state
        # machine's phase, the approved/candidate versions, per-replica
        # served versions, and the tail of the replay-stable event log.
        # The aggregator only federates ACTIVE docs, so a fleet without
        # a RolloutController simply has no board; stale/dead procs are
        # dropped by the same active-filter (their scrape is empty).
        proc_status = (snap["processes"].get(proc) or {}).get("status", "?")
        alive = proc_status == "alive"

        def rcell(key):
            v = doc.get(key)
            return v if alive and v is not None else "-"

        versions = doc.get("versions") or {}
        vcells = "  ".join(
            f"{rid}={'-' if v is None else v}"
            for rid, v in sorted(versions.items()))
        lines.append("")
        lines.append(
            f"rollout via {proc}: phase={rcell('phase')} "
            f"approved={rcell('approved_version')} "
            f"candidate={rcell('candidate_version')} "
            f"canary={rcell('canary')} skew={rcell('skew')} "
            f"age={doc.get('age_s', 0):.0f}s "
            f"promoted={rcell('rollouts')} rolled_back={rcell('rollbacks')}")
        if vcells:
            lines.append(f"  versions: {vcells}")
        events = doc.get("events") or []
        for ev in events[-5:]:
            extras = " ".join(
                f"{k}={ev[k]}" for k in ("version", "replica", "tier", "to")
                if ev.get(k) is not None)
            lines.append(f"  #{ev.get('seq', '?'):<4} "
                         f"{ev.get('kind', '?'):<20} {extras}")
        if doc.get("digest"):
            lines.append(f"  digest: {doc['digest']}")
    for proc, doc in sorted((snap.get("per_tenants") or {}).items()):
        # Per-tenant cost board (obs/tenancy.py). Untagged requests
        # already bill as tenant "default" in the ledger, so they show
        # up here as a row, never silently dropped; a stale/dead proc
        # renders '-' in every signal column, same contract as the
        # LOAD/SPEC columns above.
        proc_status = (snap["processes"].get(proc) or {}).get("status", "?")
        alive = proc_status == "alive"
        totals = doc.get("totals") or {}

        def tstat(key):
            v = totals.get(key)
            return v if alive and v is not None else "-"

        lines.append("")
        lines.append(f"tenants via {proc}: submitted={tstat('submitted')} "
                     f"decode_tokens={tstat('decode_tokens')} "
                     f"requeues={tstat('requeues')}")
        lines.append(f"  {'TENANT':<12} {'REQS':>5} {'DONE':>5} "
                     f"{'PREFILL':>8} {'DECODE':>7} {'KV-S':>9} "
                     f"{'SPEC':>6} {'GOODPUT':>8} {'BURN':>6}")
        for tenant, row in sorted((doc.get("tenants") or {}).items()):
            spec = (row.get("spec") or {}).get("accept_rate")
            good = (row.get("goodput") or {}).get("ratio")
            burn = (row.get("goodput") or {}).get("burn_worst")

            def cell(v, fmt="{}"):
                return fmt.format(v) if alive and v is not None else "-"

            lines.append(
                f"  {tenant:<12} {cell(row.get('submitted')):>5} "
                f"{cell(row.get('completed')):>5} "
                f"{cell(row.get('prefill_tokens')):>8} "
                f"{cell(row.get('decode_tokens')):>7} "
                f"{cell(row.get('kv_block_seconds'), '{:.2f}'):>9} "
                f"{cell(None if spec is None else 100.0 * spec, '{:.0f}%'):>6} "
                f"{cell(None if good is None else 100.0 * good, '{:.1f}%'):>8} "
                f"{cell(burn, '{:.2f}'):>6}")
    for proc, doc in sorted((snap.get("trials") or {}).items()):
        proc_status = (snap["processes"].get(proc) or {}).get("status", "?")
        counts = doc.get("counts") or {}
        best = doc.get("best") or {}
        digest = doc.get("search_digest")
        lines.append("")
        lines.append(
            f"trials via {proc}: "
            + "  ".join(f"{k}={counts.get(k, 0)}" for k in
                        ("running", "paused", "promoted", "completed",
                         "pruned"))
            + f"  epochs={doc.get('epochs_spent', 0)}"
            + (f"  digest={digest}" if digest else ""))
        lines.append(f"  {'TRIAL':<7} {'STATUS':<10} {'RUNG':>4} "
                     f"{'LOSS':>10} {'RESUMED':>7} {'DIGEST':<14}")
        for tid, card in sorted((doc.get("trials") or {}).items(),
                                key=lambda kv: int(kv[0])):
            # A stale/dead runner's cards stopped updating — render the
            # signal columns '-' like every other board.
            alive = proc_status == "alive"
            loss = card.get("loss")
            mark = " *" if best and card.get("trial") == best.get("trial") \
                else ""
            lines.append(
                f"  {str(card.get('trial', tid)):<7} "
                f"{(str(card.get('status', '?')) if alive else '-'):<10} "
                f"{(str(card.get('rung', '-')) if alive else '-'):>4} "
                f"{(f'{loss:.5g}' if alive and loss is not None else '-'):>10} "
                f"{(str(card.get('resumed', 0)) if alive else '-'):>7} "
                f"{str(card.get('digest', '-')):<14}{mark}")
    workers = snap["workers"]
    if workers["workers"]:
        lines.append("")
        lines.append(f"workers (cluster ledger): "
                     f"total_updates={workers['total_updates']}")
        for wid, row in sorted(workers["workers"].items()):
            lines.append(f"  {wid:<12} updates={row.get('updates', '?')} "
                         f"lag_max={row.get('lag_max', '?')} "
                         f"sync={_sync_cell(row)}")
    alerts = snap["alerts"]
    if alerts["active"] or alerts["fired_total"]:
        lines.append("")
        lines.append(f"alerts: active={len(alerts['active'])} "
                     f"fired={alerts['fired_total']} "
                     f"kinds={','.join(alerts['fired_kinds']) or '-'}")
        for a in alerts["active"]:
            lines.append(f"  [{a['proc']}] {a['rule']} on {a['metric']}")
    return "\n".join(lines)


def main(argv: Optional[List[str]] = None) -> dict:
    ap = argparse.ArgumentParser(
        description="Merged text view over N opsd endpoints")
    ap.add_argument("endpoints", nargs="+",
                    help="ops URLs, bare or name=url")
    ap.add_argument("--interval", type=float, default=None,
                    help="repoll every N seconds (default: one shot)")
    ap.add_argument("--dead-after", type=float, default=10.0,
                    help="seconds without a successful poll before an "
                         "unreachable process reads dead (default 10)")
    ap.add_argument("--timeout", type=float, default=2.0,
                    help="per-request scrape timeout (default 2)")
    ap.add_argument("--json", action="store_true",
                    help="dump the raw merged snapshot instead of a table")
    args = ap.parse_args(argv)

    agg = FleetAggregator(dead_after=args.dead_after, timeout=args.timeout)
    for spec in args.endpoints:
        if "=" in spec and not spec.startswith("http"):
            name, url = spec.split("=", 1)
            agg.add(url, name=name)
        else:
            agg.add(spec)

    snap = {}
    while True:
        agg.poll()
        snap = agg.snapshot()
        if args.json:
            print(json.dumps(snap, indent=1))
        else:
            print(render(snap))
        if args.interval is None:
            break
        try:
            time.sleep(args.interval)
            print()
        except KeyboardInterrupt:
            break
    return snap


if __name__ == "__main__":
    sys.exit(0 if main() else 1)
